"""Dataset preprocessors: fit distributed statistics, transform lazily.

Equivalent of the reference's preprocessor library
(reference: python/ray/data/preprocessors/ — scaler.py, encoder.py,
imputer.py, concatenator.py, chain.py). Fit aggregates per-column
statistics with one task per block combined on the driver (numbers
only — never rows); transform is a lazy `map_batches` so it fuses into
the dataset's per-block pipeline and streams, TPU-style: the output of
`Concatenator` is a single contiguous float matrix per batch, ready
for `device_put` without row-wise python.
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as B


class Preprocessor:
    """fit(ds) learns state; transform(ds) appends a lazy batch op."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit before transform")
        fn = self._transform_batch  # bound method pickles with the state
        return ds.map_batches(fn, batch_format="numpy")

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return self._transform_batch(dict(batch))

    # subclass hooks
    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds) -> None:
        raise NotImplementedError

    def _transform_batch(self, batch):
        raise NotImplementedError


@ray_tpu.remote
def _column_moments(blk, ops, columns):
    """(count, sum, sumsq, min, max) per column for one block."""
    from ray_tpu.data.dataset import _apply_ops_local

    blk = _apply_ops_local(blk, ops)
    out = {}
    for c in columns:
        v = np.asarray(blk.column(c).to_numpy(zero_copy_only=False), dtype=np.float64)
        v = v[~np.isnan(v)]
        if len(v) == 0:
            out[c] = (0, 0.0, 0.0, np.inf, -np.inf)
        else:
            out[c] = (len(v), float(v.sum()), float((v * v).sum()), float(v.min()), float(v.max()))
    return out


@ray_tpu.remote
def _column_uniques(blk, ops, columns):
    from ray_tpu.data.dataset import _apply_ops_local

    blk = _apply_ops_local(blk, ops)
    return {c: list(set(blk.column(c).to_pylist())) for c in columns}


def _per_block(ds, task, columns):
    """One fan-out task per block, results gathered on the driver — the
    shared scaffolding behind every distributed fit. `_exchange_inputs`
    resolves any global Limit first (a per-block limit inside the fit
    task would over-count)."""
    refs, chain = ds._exchange_inputs()
    ops = ray_tpu.put(chain) if chain else None
    return ray_tpu.get([task.remote(r, ops, columns) for r in refs])


def _gather_moments(ds, columns) -> Dict[str, Dict[str, float]]:
    parts = _per_block(ds, _column_moments, columns)
    stats = {}
    for c in columns:
        n = sum(p[c][0] for p in parts)
        s = sum(p[c][1] for p in parts)
        ss = sum(p[c][2] for p in parts)
        mn = min(p[c][3] for p in parts)
        mx = max(p[c][4] for p in parts)
        mean = s / n if n else 0.0
        var = max(ss / n - mean * mean, 0.0) if n else 0.0
        stats[c] = {"count": n, "mean": mean, "std": var**0.5, "min": mn, "max": mx}
    return stats


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Dict[str, float]] = {}

    def _fit(self, ds):
        self.stats_ = _gather_moments(ds, self.columns)

    def _transform_batch(self, batch):
        for c in self.columns:
            st = self.stats_[c]
            std = st["std"] or 1.0
            batch[c] = (np.asarray(batch[c], np.float64) - st["mean"]) / std
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference: scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Dict[str, float]] = {}

    def _fit(self, ds):
        self.stats_ = _gather_moments(ds, self.columns)

    def _transform_batch(self, batch):
        for c in self.columns:
            st = self.stats_[c]
            span = (st["max"] - st["min"]) or 1.0
            batch[c] = (np.asarray(batch[c], np.float64) - st["min"]) / span
        return batch


class LabelEncoder(Preprocessor):
    """Map category values to dense int codes (reference: encoder.py)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.mapping_: Dict[Any, int] = {}

    def _fit(self, ds):
        parts = _per_block(ds, _column_uniques, [self.label_column])
        values = sorted({v for p in parts for v in p[self.label_column]}, key=str)
        self.mapping_ = {v: i for i, v in enumerate(values)}

    def _transform_batch(self, batch):
        m = self.mapping_
        batch[self.label_column] = np.asarray([m[v] for v in batch[self.label_column]], np.int64)
        return batch


class OneHotEncoder(Preprocessor):
    """Expand each category column into 0/1 indicator columns
    (reference: encoder.py OneHotEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.categories_: Dict[str, List[Any]] = {}

    def _fit(self, ds):
        parts = _per_block(ds, _column_uniques, self.columns)
        for c in self.columns:
            self.categories_[c] = sorted({v for p in parts for v in p[c]}, key=str)

    def _transform_batch(self, batch):
        for c in self.columns:
            vals = batch.pop(c)
            for cat in self.categories_[c]:
                batch[f"{c}_{cat}"] = np.asarray([v == cat for v in vals], np.int8)
        return batch


class SimpleImputer(Preprocessor):
    """Fill NaNs with the column mean or a constant (reference: imputer.py)."""

    def __init__(self, columns: List[str], strategy: str = "mean", fill_value: Optional[float] = None):
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown imputing strategy {strategy!r}")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, Dict[str, float]] = {}

    def _needs_fit(self):
        return self.strategy == "mean"

    def _fit(self, ds):
        if self.strategy == "mean":
            self.stats_ = _gather_moments(ds, self.columns)

    def _transform_batch(self, batch):
        for c in self.columns:
            fill = self.stats_[c]["mean"] if self.strategy == "mean" else self.fill_value
            v = np.asarray(batch[c], np.float64)
            batch[c] = np.where(np.isnan(v), fill, v)
        return batch


class Concatenator(Preprocessor):
    """Merge numeric columns into one contiguous float feature matrix —
    the device_put-ready layout (reference: concatenator.py)."""

    def __init__(self, columns: List[str], output_column_name: str = "concat_out",
                 dtype=np.float32, exclude: Optional[List[str]] = None):
        self.columns = columns
        self.output_column_name = output_column_name
        self.dtype = dtype
        self.exclude = exclude or []

    def _needs_fit(self):
        return False

    def _fit(self, ds):
        pass

    def _transform_batch(self, batch):
        cols = [c for c in self.columns if c not in self.exclude]
        mat = np.stack([np.asarray(batch.pop(c), self.dtype) for c in cols], axis=1)
        batch[self.output_column_name] = mat
        return batch


class Chain(Preprocessor):
    """Run preprocessors in sequence; fit respects upstream transforms
    (reference: chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def _needs_fit(self):
        return any(p._needs_fit() for p in self.preprocessors)

    def fit(self, ds) -> "Chain":
        for p in self.preprocessors:
            ds = p.fit(ds).transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch


class Tokenizer(Preprocessor):
    """String columns → token lists (reference: preprocessors/tokenizer.py
    Tokenizer — default whitespace split, custom `tokenization_fn`
    supported). Stateless: no fit."""

    def __init__(self, columns: List[str], tokenization_fn=None):
        self.columns = columns
        self.tokenization_fn = tokenization_fn or (lambda s: s.split())

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        fn = self.tokenization_fn
        for c in self.columns:
            batch[c] = np.asarray(
                [fn(str(v)) for v in batch[c]], dtype=object
            )
        return batch


class FeatureHasher(Preprocessor):
    """Token counts → fixed-width hashed count vectors (reference:
    preprocessors/hasher.py FeatureHasher — the hashing trick: no
    vocabulary state, collisions accepted). Input columns hold strings
    (whitespace-tokenized) or token lists; output column `{col}_hashed`
    holds float32[num_features] rows. The hash is md5-based so feature
    indices are stable across processes (PYTHONHASHSEED-proof)."""

    def __init__(self, columns: List[str], num_features: int = 256):
        self.columns = columns
        self.num_features = num_features

    def _needs_fit(self) -> bool:
        return False

    def _hash(self, token: str) -> int:
        return int(hashlib.md5(token.encode()).hexdigest()[:8], 16) % self.num_features

    def _transform_batch(self, batch):
        for c in self.columns:
            rows = []
            for v in batch[c]:
                toks = v if isinstance(v, (list, np.ndarray)) else str(v).split()
                row = np.zeros(self.num_features, np.float32)
                for t in toks:
                    row[self._hash(str(t))] += 1.0
                rows.append(row)
            batch[f"{c}_hashed"] = np.stack(rows) if rows else np.zeros((0, self.num_features), np.float32)
            del batch[c]
        return batch


@ray_tpu.remote
def _column_token_counts(blk, ops, columns):
    from collections import Counter

    from ray_tpu.data.dataset import _apply_ops_local

    blk = _apply_ops_local(blk, ops)
    out = {}
    for c in columns:
        counts: Counter = Counter()
        for v in blk.column(c).to_pylist():
            toks = v if isinstance(v, list) else str(v).split()
            counts.update(str(t) for t in toks)
        out[c] = dict(counts)
    return out


class CountVectorizer(Preprocessor):
    """Strings → vocabulary count vectors (reference:
    preprocessors/vectorizer.py CountVectorizer). Fit builds the
    vocabulary as a distributed token-count aggregation (one task per
    block, counts merged on the driver — never rows); `max_features`
    keeps the most frequent tokens. Output column `{col}_counts` holds
    float32[|vocab|] rows; the vocabulary order is frequency-descending
    then lexicographic, deterministic across runs."""

    def __init__(self, columns: List[str], max_features: Optional[int] = None):
        self.columns = columns
        self.max_features = max_features
        self.vocabularies: Dict[str, Dict[str, int]] = {}

    def _fit(self, ds) -> None:
        from collections import Counter

        parts = _per_block(ds, _column_token_counts, self.columns)
        for c in self.columns:
            total: Counter = Counter()
            for p in parts:
                total.update(p[c])
            items = sorted(total.items(), key=lambda kv: (-kv[1], kv[0]))
            if self.max_features:
                items = items[: self.max_features]
            self.vocabularies[c] = {tok: i for i, (tok, _n) in enumerate(items)}

    def _transform_batch(self, batch):
        for c in self.columns:
            vocab = self.vocabularies[c]
            rows = []
            for v in batch[c]:
                toks = v if isinstance(v, (list, np.ndarray)) else str(v).split()
                row = np.zeros(len(vocab), np.float32)
                for t in toks:
                    i = vocab.get(str(t))
                    if i is not None:
                        row[i] += 1.0
                rows.append(row)
            batch[f"{c}_counts"] = np.stack(rows) if rows else np.zeros((0, len(vocab)), np.float32)
            del batch[c]
        return batch


class UniformKBinsDiscretizer(Preprocessor):
    """Numeric columns → equal-width bin indices (reference:
    preprocessors/discretizer.py UniformKBinsDiscretizer). Fit gathers
    per-column min/max through the distributed moments pass; transform
    maps values to int64 bins [0, bins-1] (values at max land in the
    last bin; NaN stays NaN as a float column would — emitted as -1)."""

    def __init__(self, columns: List[str], bins: int = 10):
        self.columns = columns
        self.bins = bins
        self.ranges: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        stats = _gather_moments(ds, self.columns)
        self.ranges = {c: (stats[c]["min"], stats[c]["max"]) for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            lo, hi = self.ranges[c]
            width = (hi - lo) / self.bins if hi > lo else 1.0
            v = np.asarray(batch[c], np.float64)
            # mask NaN BEFORE the int cast: casting NaN to int64 is
            # undefined behavior and warns per batch
            nan = np.isnan(v)
            idx = np.clip(
                ((np.where(nan, lo, v) - lo) / width).astype(np.int64),
                0, self.bins - 1,
            )
            batch[c] = np.where(nan, -1, idx).astype(np.int64)
        return batch


class CustomKBinsDiscretizer(Preprocessor):
    """Numeric columns → bins with EXPLICIT edges (reference:
    preprocessors/discretizer.py CustomKBinsDiscretizer). No fit:
    `bin_edges[col]` is the full monotonic edge list; np.digitize
    semantics, clipped to [0, len(edges)-2]."""

    def __init__(self, columns: List[str], bin_edges: Dict[str, List[float]]):
        self.columns = columns
        self.bin_edges = {c: np.asarray(e, np.float64) for c, e in bin_edges.items()}

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        for c in self.columns:
            edges = self.bin_edges[c]
            v = np.asarray(batch[c], np.float64)
            idx = np.clip(np.digitize(v, edges) - 1, 0, len(edges) - 2)
            batch[c] = np.where(np.isnan(v), -1, idx).astype(np.int64)
        return batch
