"""WebDataset format — tar archives of grouped sample files.

Equivalent of the reference's webdataset datasource
(reference: python/ray/data/datasource/webdataset_datasource.py, which
wraps the `webdataset` package's tar conventions). Implemented natively
on `tarfile` — the format is just a POSIX tar whose members share a
basename stem per sample (`0001.jpg`, `0001.cls`, `0001.json` → one
row) — so TPU input pipelines can stream WebDataset shards without the
torch-ecosystem dependency.

Decoding by extension (reference: webdataset autodecode defaults):
  .json → parsed object      .cls/.id → int        .txt → str
  .jpg/.jpeg/.png → HWC uint8 array (via PIL, if installed)
  .npy → numpy array         anything else → raw bytes
"""
from __future__ import annotations

import io
import json
import tarfile
from typing import Any, Dict, Iterable, List


def decode_member(ext: str, data: bytes, decode_images: bool = True) -> Any:
    ext = ext.lower()
    if ext == "json":
        return json.loads(data)
    if ext in ("cls", "id"):
        return int(data.decode().strip())
    if ext == "txt":
        return data.decode()
    if ext == "npy":
        import numpy as np

        return np.load(io.BytesIO(data), allow_pickle=False)
    if decode_images and ext in ("jpg", "jpeg", "png", "ppm", "bmp"):
        try:
            import numpy as np
            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        except ImportError:
            return data
    return data


def encode_member(ext: str, value: Any) -> bytes:
    ext = ext.lower()
    if isinstance(value, bytes):
        return value
    # extension dictates the codec BEFORE generic type dispatch: a list
    # under an .npy column is an array (block storage returns tensor
    # columns as lists), not a JSON document
    if ext == "npy":
        import numpy as np

        buf = io.BytesIO()
        np.save(buf, np.asarray(value), allow_pickle=False)
        return buf.getvalue()
    if ext == "json" or isinstance(value, (dict, list)):
        return json.dumps(value).encode()
    if ext in ("jpg", "jpeg", "png"):
        import numpy as np
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(np.asarray(value)).save(buf, format="PNG" if ext == "png" else "JPEG")
        return buf.getvalue()
    return str(value).encode()


def read_samples(f, decode_images: bool = True) -> List[Dict[str, Any]]:
    """Stream one tar shard into rows, grouping consecutive members by
    basename stem (webdataset's on-the-wire contract: a sample's files
    are adjacent in the archive)."""
    rows: List[Dict[str, Any]] = []
    current: Dict[str, Any] = {}
    key = None
    with tarfile.open(fileobj=f, mode="r|*") as tar:
        for member in tar:
            if not member.isfile():
                continue
            name = member.name
            # stem: up to the FIRST dot of the basename (webdataset keys
            # may contain directories; extensions may be compound)
            base = name.rsplit("/", 1)[-1]
            dot = base.find(".")
            stem, ext = (base[:dot], base[dot + 1 :]) if dot >= 0 else (base, "")
            prefix = name[: len(name) - len(base)]
            sample_key = prefix + stem
            if key is not None and sample_key != key:
                rows.append(current)
                current = {}
            key = sample_key
            current["__key__"] = sample_key
            data = tar.extractfile(member).read()
            current[ext or "bin"] = decode_member(ext, data, decode_images)
    if current:
        rows.append(current)
    return rows


def write_samples(f, rows: Iterable[Dict[str, Any]]) -> None:
    """Write rows as one tar shard; every non-``__key__`` column becomes
    a `<key>.<column>` member."""
    with tarfile.open(fileobj=f, mode="w") as tar:
        for i, row in enumerate(rows):
            key = str(row.get("__key__", f"{i:08d}"))
            for col, value in row.items():
                if col == "__key__":
                    continue
                payload = encode_member(col, value)
                info = tarfile.TarInfo(name=f"{key}.{col}")
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
