"""TFRecord datasource — self-contained reader/writer.

Reference surface: python/ray/data/datasource/tfrecords_datasource.py
(tf.train.Example records). TPU-first difference: NO tensorflow import
on the hot path — TFRecord is just a framing format (length + masked
crc32c + payload) and tf.train.Example is three fixed proto messages, so
both are implemented directly here (a worker process should not pay a
3s/500MB tensorflow import to read its input shards). Compatibility
with real TF-written files is asserted in tests against tensorflow
itself.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

# ---------------------------------------------------------------- crc32c
# Castagnoli CRC (the TFRecord checksum), table-driven.
_CRC_TABLE = [0] * 256
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (0x82F63B78 ^ (_c >> 1)) if (_c & 1) else (_c >> 1)
    _CRC_TABLE[_i] = _c

try:  # optional C accelerator when the image ships one
    from crc32c import crc32c as _crc32c_accel  # type: ignore
except ImportError:
    _crc32c_accel = None


def _crc32c(data: bytes) -> int:
    if _crc32c_accel is not None:
        return _crc32c_accel(data)
    # pure-python fallback: plain bytes iteration over a list table
    # (~10x the numpy-per-element version; still the write-path
    # bottleneck for multi-GB datasets — ship crc32c for those)
    crc = 0xFFFFFFFF
    table = _CRC_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------------ protobuf io
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _field(tag: int, wire: int, payload: bytes) -> bytes:
    return _varint((tag << 3) | wire) + payload


def _len_field(tag: int, payload: bytes) -> bytes:
    return _field(tag, 2, _varint(len(payload)) + payload)


def encode_example(row: Dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example. Values may be int/float/str/
    bytes or (nested) lists / 1-D arrays thereof."""
    entries = []
    for key, value in row.items():
        if isinstance(value, np.ndarray):
            value = value.tolist()
        if not isinstance(value, (list, tuple)):
            value = [value]
        if len(value) and isinstance(value[0], (bytes, str)):
            items = b"".join(
                _len_field(1, v.encode() if isinstance(v, str) else v) for v in value
            )
            feature = _len_field(1, items)  # BytesList
        elif len(value) and isinstance(value[0], (float, np.floating)):
            packed = struct.pack(f"<{len(value)}f", *value)
            feature = _len_field(2, _len_field(1, packed))  # FloatList (packed)
        else:
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in value)
            feature = _len_field(3, _len_field(1, packed))  # Int64List (packed)
        entry = _len_field(1, key.encode()) + _len_field(2, feature)
        entries.append(_len_field(1, entry))  # Features.feature map entry
    features = b"".join(entries)
    return _len_field(1, features)  # Example.features


def decode_example(data: bytes) -> Dict[str, Any]:
    """serialized tf.train.Example -> dict (single-element lists unwrap
    to scalars, matching the reference reader's behavior)."""
    buf = memoryview(data)
    out: Dict[str, Any] = {}
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        if tag >> 3 != 1:
            pos = _skip(buf, pos, tag & 7)
            continue
        flen, pos = _read_varint(buf, pos)  # Features
        fend = pos + flen
        while pos < fend:
            etag, pos = _read_varint(buf, pos)
            if etag >> 3 != 1:
                pos = _skip(buf, pos, etag & 7)
                continue
            elen, pos = _read_varint(buf, pos)  # map entry
            eend = pos + elen
            key = None
            value: Any = None
            while pos < eend:
                ftag, pos = _read_varint(buf, pos)
                f, wire = ftag >> 3, ftag & 7
                if f == 1 and wire == 2:
                    klen, pos = _read_varint(buf, pos)
                    key = bytes(buf[pos : pos + klen]).decode()
                    pos += klen
                elif f == 2 and wire == 2:
                    vlen, pos = _read_varint(buf, pos)
                    value = _decode_feature(buf, pos, pos + vlen)
                    pos += vlen
                else:
                    pos = _skip(buf, pos, wire)
            if key is not None:
                out[key] = value
    return out


def _decode_feature(buf: memoryview, pos: int, end: int):
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        f, wire = tag >> 3, tag & 7
        ln, pos = _read_varint(buf, pos)
        inner_end = pos + ln
        if f == 1:  # BytesList
            vals = []
            while pos < inner_end:
                itag, pos = _read_varint(buf, pos)
                iln, pos = _read_varint(buf, pos)
                vals.append(bytes(buf[pos : pos + iln]))
                pos += iln
            return vals[0] if len(vals) == 1 else vals
        if f == 2:  # FloatList
            vals_f: List[float] = []
            while pos < inner_end:
                itag, pos = _read_varint(buf, pos)
                if itag & 7 == 2:  # packed
                    iln, pos = _read_varint(buf, pos)
                    vals_f.extend(struct.unpack(f"<{iln // 4}f", bytes(buf[pos : pos + iln])))
                    pos += iln
                else:  # unpacked fixed32
                    vals_f.append(struct.unpack("<f", bytes(buf[pos : pos + 4]))[0])
                    pos += 4
            return vals_f[0] if len(vals_f) == 1 else vals_f
        if f == 3:  # Int64List
            vals_i: List[int] = []
            while pos < inner_end:
                itag, pos = _read_varint(buf, pos)
                if itag & 7 == 2:  # packed
                    iln, pos = _read_varint(buf, pos)
                    pend = pos + iln
                    while pos < pend:
                        v, pos = _read_varint(buf, pos)
                        vals_i.append(v - (1 << 64) if v >= (1 << 63) else v)
                else:
                    v, pos = _read_varint(buf, pos)
                    vals_i.append(v - (1 << 64) if v >= (1 << 63) else v)
            return vals_i[0] if len(vals_i) == 1 else vals_i
        pos = inner_end
    return None


def _skip(buf: memoryview, pos: int, wire: int) -> int:
    if wire == 0:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire == 2:
        ln, pos = _read_varint(buf, pos)
        return pos + ln
    if wire == 5:
        return pos + 4
    if wire == 1:
        return pos + 8
    raise ValueError(f"unsupported wire type {wire}")


# ------------------------------------------------------------ record framing
def write_records(f, payloads: Iterable[bytes]) -> None:
    for data in payloads:
        header = struct.pack("<Q", len(data))
        f.write(header)
        f.write(struct.pack("<I", _masked_crc(header)))
        f.write(data)
        f.write(struct.pack("<I", _masked_crc(data)))


def read_records(f, verify: bool = False):
    while True:
        header = f.read(8)
        if not header:
            return
        if len(header) < 8:
            raise ValueError("truncated tfrecord header")
        (length,) = struct.unpack("<Q", header)
        hcrc = f.read(4)
        data = f.read(length)
        dcrc = f.read(4)
        if len(data) < length:
            raise ValueError("truncated tfrecord payload")
        if verify:
            if struct.unpack("<I", hcrc)[0] != _masked_crc(header):
                raise ValueError("tfrecord header crc mismatch")
            if struct.unpack("<I", dcrc)[0] != _masked_crc(data):
                raise ValueError("tfrecord data crc mismatch")
        yield data
