"""Distributed 2-stage shuffle primitives for Dataset — the LEGACY /
fallback exchange path.

Equivalent of the reference's pull-based shuffle
(reference: python/ray/data/_internal/planner/exchange/ — the
map-partition / reduce-merge task pattern behind repartition,
random_shuffle and range-partitioned sort). The driver only touches
refs: every row moves worker-to-worker through the object store, so no
operation materializes the dataset in the driver process.

The DEFAULT shuffle path is now the streaming exchange
(`data/_internal/exchange.py`): mappers push partition chunks to
reducer actors over shm rings as they are produced, so no N×M part-ref
set ever materializes. This module remains as (a) the partition-function
library the streaming mappers share (`partition_block`), and (b) the
whole-pipeline fallback selected by
`DataContext.use_streaming_exchange = False` — its hierarchical fan-in
is the shape cross-node exchanges without a shared arena fall back to.

Map stage: each input block is split into M parts (random assignment,
range partition by sampled boundaries, or contiguous chunks). Reduce
stage: reducer j concatenates part j of every mapper (+ permutes for
shuffle / sorts for range partition).
"""
from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu
from ray_tpu.data import block as B


def partition_block(blk, mode: str, M: int, arg, seed: int):
    """Split one block into M parts (shared by the legacy map task AND
    the streaming exchange mappers): random assignment, range partition
    by sampled boundaries, contiguous chunks, or deterministic key
    hash."""
    import numpy as np

    if mode == "random":
        rng = np.random.default_rng(seed)
        assign = rng.integers(0, M, size=blk.num_rows)
        return [blk.take(np.nonzero(assign == j)[0]) for j in range(M)]
    if mode == "range":
        key, descending, boundaries = arg
        col = np.asarray(blk.column(key))
        idx = np.searchsorted(np.asarray(boundaries), col, side="right")
        if descending:
            idx = (M - 1) - idx
        return [blk.take(np.nonzero(idx == j)[0]) for j in range(M)]
    if mode == "chunk":
        start, per = arg  # global row offset of this block, rows per output
        ends = np.arange(blk.num_rows) + start
        idx = np.minimum(ends // per, M - 1)
        return [blk.take(np.nonzero(idx == j)[0]) for j in range(M)]
    if mode == "hash":
        # deterministic key hash (Python's str hash is seed-randomized
        # PER PROCESS — using it would scatter one key across reducers)
        key = arg
        idx = _hash_partition_index(blk.column(key), M)
        return [blk.take(np.nonzero(idx == j)[0]) for j in range(M)]
    raise ValueError(f"unknown partition mode {mode}")


def finalize_partition(blk, mode: str, reduce_arg, seed: int):
    """Per-partition post-merge step (shared with the streaming
    reducers): permute for random shuffle, sort for range partition."""
    import numpy as np

    if mode == "random":
        rng = np.random.default_rng(seed)
        return blk.take(rng.permutation(blk.num_rows))
    if mode == "range":
        key, descending = reduce_arg
        return blk.sort_by([(key, "descending" if descending else "ascending")])
    return blk


@ray_tpu.remote
def _map_partition(blk, ops, mode: str, M: int, arg, seed: int):
    from ray_tpu.data.dataset import _apply_ops_local

    blk = _apply_ops_local(blk, ops)
    if M == 1:
        # with num_returns=1 the executor treats the return value itself
        # as the single result — a 1-tuple would arrive as a tuple
        return blk
    return tuple(partition_block(blk, mode, M, arg, seed))


def _hash_partition_index(col, M: int):
    """Deterministic partition index per value — same value → same
    partition in EVERY mapper process (groupby correctness depends on
    it). Numeric columns hash arithmetically; strings/bytes via crc32."""
    import numpy as np
    import pyarrow as pa

    if pa.types.is_integer(col.type):
        return (np.asarray(col).astype(np.int64) % M + M) % M
    if pa.types.is_floating(col.type):
        v = np.asarray(col)
        iv = v.view(np.int64) if v.dtype == np.float64 else v.astype(np.float64).view(np.int64)
        return ((iv % M) + M) % M
    import zlib

    vals = col.to_pylist()
    out = np.empty(len(vals), np.int64)
    for i, v in enumerate(vals):
        if isinstance(v, bytes):
            out[i] = zlib.crc32(v)
        else:
            out[i] = zlib.crc32(str(v).encode())
    return out % M


@ray_tpu.remote
def _reduce_merge(mode: str, arg, seed: int, *parts):
    return finalize_partition(B.concat_blocks(list(parts)), mode, arg, seed)


@ray_tpu.remote
def _block_count(blk, ops):
    from ray_tpu.data.dataset import _apply_ops_local

    return _apply_ops_local(blk, ops).num_rows


@ray_tpu.remote
def _sample_keys(blk, ops, key: str, k: int, seed: int):
    import numpy as np

    from ray_tpu.data.dataset import _apply_ops_local

    blk = _apply_ops_local(blk, ops)
    col = np.asarray(blk.column(key))
    if len(col) == 0:
        return col
    rng = np.random.default_rng(seed)
    return col[rng.integers(0, len(col), size=min(k, len(col)))]


def shuffle_exchange(
    block_refs: List[Any],
    ops,
    mode: str,
    M: int,
    arg=None,
    reduce_arg=None,
    seed: Optional[int] = None,
    per_map_args: Optional[List[Any]] = None,
    ops_ref=None,
) -> List[Any]:
    """Run the 2-stage exchange; returns M reduced block refs. Callers
    that already put the ops chain pass `ops_ref` so it is shared rather
    than re-put per stage."""
    base_seed = 0 if seed is None else seed
    if ops_ref is None:
        ops_ref = ray_tpu.put(ops) if ops else None
    parts: List[List[Any]] = []
    for i, ref in enumerate(block_refs):
        map_arg = per_map_args[i] if per_map_args is not None else arg
        out = _map_partition.options(num_returns=M).remote(
            ref, ops_ref, mode, M, map_arg, base_seed + 17 * i + 1
        )
        parts.append(out if isinstance(out, list) else [out])

    # Hierarchical reduce for large exchanges (reference: push-based
    # shuffle exists precisely because N_mappers x M_reducers part refs
    # overwhelm flat exchanges): group mappers, concat-merge each group's
    # column j, then run the REAL reduce over one partial per group —
    # a reduce call never takes more than _GROUP inputs, and the final
    # permute/sort still happens exactly once.
    _GROUP = 64
    while len(parts) > _GROUP:  # loop: even 10k+ mappers converge to <=64
        grouped: List[List[Any]] = []
        for g in range(0, len(parts), _GROUP):
            chunk = parts[g : g + _GROUP]
            grouped.append([
                _reduce_merge.remote(None, None, 0, *(p[j] for p in chunk))
                for j in range(M)
            ])
        parts = grouped
    return [
        _reduce_merge.remote(mode, reduce_arg, base_seed + 31 * j + 7, *(p[j] for p in parts))
        for j in range(M)
    ]
