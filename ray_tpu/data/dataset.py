"""Dataset — lazy, block-parallel distributed data.

Equivalent of the reference's Dataset (reference:
python/ray/data/dataset.py:142): transformations append typed logical
operators (`_internal/logical_ops.py`) to a logical plan; the optimizer
fuses narrow runs and pushes limits toward the sources
(`_internal/optimizer.py`); execution fans out per-block tasks gated by
backpressure policies, and `iter_batches` streams with a bounded
in-flight window (the role of the pull-based StreamingExecutor,
reference: data/_internal/execution/streaming_executor.py:55 — ours is a
windowed pipeline over the same task substrate, which on a TPU host's
CPU side is the data-loading path feeding device_put). Per-operator
execution stats surface through `Dataset.stats()`.
"""
from __future__ import annotations

import builtins
import itertools

import numpy as np
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data._internal import logical_ops as L

# remote transforms ---------------------------------------------------------


def _apply_ops_local(blk, ops):
    """Run an op chain (typed LogicalOps or legacy (kind, fn, kw)
    tuples) over one block — shared by the fused per-block task, the
    shuffle map stages and the preprocessor fit tasks."""
    return L.apply_ops(blk, ops)


@ray_tpu.remote
def _sort_block(blk, key, descending):
    return blk.sort_by([(key, "descending" if descending else "ascending")])


@ray_tpu.remote
def _merge_blocks(*blks):
    return B.concat_blocks(list(blks))


@ray_tpu.remote
def _block_num_rows(blk):
    return blk.num_rows


@ray_tpu.remote
def _slice_rows(blk, start, stop):
    return blk.slice(start, stop - start)


@ray_tpu.remote
def _unique_block(blk, column: str):
    col = blk.column(column).combine_chunks()
    return list(dict.fromkeys(col.to_pylist()))


@ray_tpu.remote
def _sample_block(blk, fraction: float, seed: int):
    import numpy as np

    keep = np.random.default_rng(seed).random(blk.num_rows) < fraction
    return blk.take(np.nonzero(keep)[0])


@ray_tpu.remote
def _write_parquet_block(blk, path: str):
    import pyarrow.parquet as pq

    pq.write_table(blk, path)
    return path


@ray_tpu.remote
def _write_csv_block(blk, path: str):
    import pyarrow.csv as pcsv

    pcsv.write_csv(blk, path)
    return path


@ray_tpu.remote
def _write_tfrecords_block(blk, path: str):
    from ray_tpu.data import block as B
    from ray_tpu.data.tfrecords import encode_example, write_records

    with open(path, "wb") as f:
        write_records(f, (encode_example(row) for row in B.block_rows(blk)))
    return path


@ray_tpu.remote
def _write_webdataset_block(blk, path: str):
    from ray_tpu.data import block as B
    from ray_tpu.data.webdataset import write_samples

    with open(path, "wb") as f:
        write_samples(f, B.block_rows(blk))
    return path


@ray_tpu.remote
def _zip_blocks(left, *right_parts):
    right = B.concat_blocks(list(right_parts))
    for name in right.column_names:
        out_name = name if name not in left.column_names else name + "_1"
        left = left.append_column(out_name, right.column(name).combine_chunks())
    return left


class LazyBlock:
    """A block the streaming executor launches ON PULL rather than at
    dataset construction (reference: read tasks are operators inside the
    streaming executor, data/_internal/planner/plan_read_op.py — eager
    reads would materialize the whole input ahead of the consumer and
    defeat backpressure on larger-than-arena datasets)."""

    __slots__ = ("_thunk", "_ref")

    def __init__(self, thunk):
        self._thunk = thunk
        self._ref = None

    def force(self):
        """Launch (or return the already-launched) read. The ref is CACHED
        — eager paths force the same dataset several times (stats pass +
        exchange) and must not duplicate reads."""
        if self._ref is None:
            self._ref = self._thunk()
        return self._ref

    def force_transient(self):
        """Launch WITHOUT caching: the streaming executor's form. A cached
        ref would stay alive (and owner-pinned in the arena) for the
        dataset's lifetime — the consumed-block leak that streaming
        windows exist to prevent. Re-iteration re-runs the read, matching
        un-materialized dataset semantics."""
        return self._ref if self._ref is not None else self._thunk()


def _force(r):
    return r.force() if isinstance(r, LazyBlock) else r


class Dataset:
    """Lazy dataset over block refs + a pending logical-op chain."""

    def __init__(self, block_refs: List[Any], ops: Optional[List] = None,
                 source: Optional[str] = None):
        self._block_refs = block_refs
        self._ops: List = [L.as_op(op) for op in ops or []]
        self._source = source or "Input"
        # last execution's StatsBuilder (set by the executor; see stats())
        self._stats_builder = None

    def _forced(self) -> List[Any]:
        """Source refs with any lazy reads launched (the non-streaming
        paths — shuffles, stats — need them all in flight at once)."""
        return [_force(r) for r in self._block_refs]

    def _exchange_inputs(self):
        """(source refs, ops chain) safe to apply independently per
        block inside exchange/fit map tasks. A global Limit (or an
        earlier Exchange) cannot be applied per block, so chains
        containing one execute first."""
        from ray_tpu.data._internal.optimizer import has_barrier

        if has_barrier(self._ops):
            return self._execute_refs(), []
        return self._forced(), self._ops

    def _use_streaming_exchange(self) -> bool:
        from ray_tpu.data.context import DataContext

        return DataContext.get_current().use_streaming_exchange

    # ------------------------------------------------------------ transforms
    def _with_op(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [op], source=self._source)

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with_op(L.MapRows(fn))

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute: Optional[str] = None, num_actors: int = 2,
                    fn_constructor_args=None, fn_constructor_kwargs=None,
                    ray_actor_options: Optional[Dict] = None, **kw) -> "Dataset":
        """Per-batch transform. compute="actors" runs it on a pool of
        `num_actors` STATEFUL workers — `fn` may be a class constructed
        once per worker (reference: actor_pool_map_operator.py; the
        TPU-host shape for tokenizers/encoders too expensive to build per
        task)."""
        return self._with_op(L.MapBatches(
            fn, batch_format=batch_format, compute=compute,
            num_actors=num_actors, fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs,
            ray_actor_options=ray_actor_options,
        ))

    def flat_map(self, fn) -> "Dataset":
        return self._with_op(L.FlatMap(fn))

    def filter(self, fn) -> "Dataset":
        return self._with_op(L.Filter(fn))

    def add_column(self, name: str, fn) -> "Dataset":
        return self._with_op(L.AddColumn(name, fn))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._with_op(L.DropColumns(cols))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._with_op(L.SelectColumns(cols))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._with_op(L.RenameColumns(mapping))

    def limit(self, n: int) -> "Dataset":
        """First n rows, as a logical op: the optimizer pushes it past
        row-count-preserving operators and the executor stops pulling
        sources once the budget is met — `read_*(...).limit(k)` launches
        only the needed prefix of read tasks."""
        return self._with_op(L.Limit(n))

    # ------------------------------------------------------------- execution
    def _execute_refs(self) -> List[Any]:
        """Launch per-block pipelines; returns refs of transformed blocks."""
        if not self._ops:
            return self._forced()
        from ray_tpu.data._executor import execute_eager

        return execute_eager(
            self._block_refs, self._ops, owner=self, input_name=self._source
        )

    def materialize(self) -> "Dataset":
        refs = self._execute_refs()
        ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
        if self._stats_builder is not None:
            # the eager path launches without waiting; every block is
            # done HERE, so this is the execution's true end time
            self._stats_builder.finalize()
        out = Dataset(refs, source=self._source)
        out._stats_builder = self._stats_builder
        return out

    def stats(self):
        """Per-operator stats of the LAST execution (iterate, take,
        materialize, ... first): wall time, task counts, rows/bytes
        in/out and backpressure-throttle counts. Returns a DatasetStats
        — str() is the human-readable report, `.to_dict()` the
        programmatic form (reference: Dataset.stats())."""
        from ray_tpu.data._internal.stats import EMPTY_STATS

        if self._stats_builder is None:
            return EMPTY_STATS
        return self._stats_builder.build()

    def blocks(self) -> List[Any]:
        return self._execute_refs()

    # ------------------------------------------------------------ reshaping
    # All three reshaping ops run as distributed exchanges — the driver
    # only moves refs, never rows. The DEFAULT path appends a streaming
    # Exchange operator to the plan (data/_internal/exchange.py: mappers
    # push partition chunks to reducer actors over shm rings as blocks
    # arrive, object-plane fallback across nodes, backpressure via the
    # executor's policies). `DataContext.use_streaming_exchange = False`
    # restores the seed-era 2-stage shuffle (data/_shuffle.py).

    def repartition(self, num_blocks: int) -> "Dataset":
        from ray_tpu.data._shuffle import _block_count, shuffle_exchange

        if not self._block_refs:
            return Dataset([])
        src_refs, ops = self._exchange_inputs()
        ops_ref = ray_tpu.put(ops) if ops else None
        # chunk partitioning needs each mapper's global row offset: a
        # counts prepass (integers only) — shared by both paths
        counts = ray_tpu.get([_block_count.remote(r, ops_ref) for r in src_refs])
        total = sum(counts)
        per = max(1, (total + num_blocks - 1) // num_blocks)
        offsets = []
        acc = 0
        for c in counts:
            offsets.append((acc, per))
            acc += c
        if self._use_streaming_exchange():
            return Dataset(src_refs, ops, source=self._source)._with_op(
                L.Exchange("chunk", num_blocks, per_map_args=offsets)
            )
        refs = shuffle_exchange(
            src_refs, ops, "chunk", num_blocks, per_map_args=offsets, ops_ref=ops_ref
        )
        return Dataset(refs)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        from ray_tpu.data._shuffle import shuffle_exchange

        if not self._block_refs:
            return Dataset([])
        if self._use_streaming_exchange():
            # pure plan rewrite — no prepass: a Limit earlier in the
            # chain becomes a LimitStage ahead of the ExchangeStage.
            # num_blocks (not len(block_refs)): an earlier Exchange in
            # the chain (repartition) changes the block count and M must
            # follow it, as the legacy path's post-barrier refs do
            M = max(1, self.num_blocks())
            return self._with_op(L.Exchange("random", M, seed=seed))
        src_refs, ops = self._exchange_inputs()
        M = max(1, len(src_refs))
        refs = shuffle_exchange(src_refs, ops, "random", M, seed=seed)
        return Dataset(refs)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Range-partitioned distributed sort: sample key quantiles, range
        partition every block, sort each range (reference: data sort via
        SortTaskSpec boundary sampling)."""
        import numpy as np

        from ray_tpu.data._shuffle import _sample_keys, shuffle_exchange

        if not self._block_refs:
            return Dataset([])
        src_refs, ops = self._exchange_inputs()
        M = max(1, len(src_refs))
        ops_ref = ray_tpu.put(ops) if ops else None
        samples = ray_tpu.get(
            [_sample_keys.remote(r, ops_ref, key, 64, 11 * i) for i, r in enumerate(src_refs)]
        )
        allkeys = np.sort(np.concatenate([s for s in samples if len(s)]))
        if len(allkeys) == 0 or M == 1:
            boundaries = []
        else:
            qs = [len(allkeys) * j // M for j in builtins.range(1, M)]
            boundaries = list(allkeys[qs])
        if self._use_streaming_exchange():
            return Dataset(src_refs, ops, source=self._source)._with_op(
                L.Exchange(
                    "range", M, arg=(key, descending, boundaries),
                    reduce_arg=(key, descending),
                )
            )
        refs = shuffle_exchange(
            src_refs,
            ops,
            "range",
            M,
            arg=(key, descending, boundaries),
            reduce_arg=(key, descending),
            ops_ref=ops_ref,
        )
        return Dataset(refs)

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(self._execute_refs() + other._execute_refs())

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (reference: Dataset.unique) —
        per-block distinct in tasks, merged on the driver."""
        parts = ray_tpu.get([
            _unique_block.remote(ref, column) for ref in self._execute_refs()
        ])
        seen: Dict[Any, None] = {}
        for p in parts:
            for v in p:
                seen.setdefault(v, None)
        return list(seen)

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: Dataset.random_sample)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        # seed=None must give per-call randomness (and seed=0 must not
        # alias it), so draw a fresh base only when seed is truly absent
        base = int(np.random.default_rng().integers(2**31)) if seed is None else seed
        return Dataset([
            LazyBlock(lambda r=ref, i=i: _sample_block.remote(r, fraction, base + i))
            for i, ref in enumerate(self._execute_refs())
        ])

    def split(self, n: int) -> List["Dataset"]:
        refs = self._execute_refs()
        out = []
        per = max(1, (len(refs) + n - 1) // n)
        for i in builtins.range(n):
            chunk = refs[i * per : (i + 1) * per]
            out.append(Dataset(chunk if chunk else []))
        return out

    def groupby(self, key: str):
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    # ----------------------------------------------------------- consumption
    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        prefetch_blocks: int = 2,
        drop_last: bool = False,
    ) -> Iterator[Any]:
        """Streaming iteration through the pull-based executor: each
        stage keeps at most its window in flight ahead of the consumer,
        so a slow consumer bounds both compute and arena footprint
        (reference: streaming_executor.py backpressure)."""
        if not self._block_refs:
            return
        from ray_tpu.data._executor import execute_streaming

        ref_iter = execute_streaming(
            self._block_refs, self._ops, max_in_flight=2 * (prefetch_blocks + 1),
            owner=self, input_name=self._source,
        )

        leftover = None
        for ref in ref_iter:
            blk = ray_tpu.get(ref)
            if leftover is not None and leftover.num_rows > 0:
                blk = B.concat_blocks([leftover, blk])
                leftover = None
            off = 0
            while off + batch_size <= blk.num_rows:
                yield B.block_to_batch(blk.slice(off, batch_size), batch_format)
                off += batch_size
            leftover = blk.slice(off)
        if leftover is not None and leftover.num_rows > 0 and not drop_last:
            yield B.block_to_batch(leftover, batch_format)

    def iter_torch_batches(self, *, batch_size: int = 256, device=None,
                           dtypes=None, drop_last: bool = False) -> Iterator[Any]:
        """Batches as dicts of torch tensors (reference:
        data/iterator.py iter_torch_batches). CPU torch by default."""
        import numpy as np
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                if getattr(v, "dtype", None) is not None and v.dtype.kind in "OUS":
                    out[k] = v  # strings/objects pass through untensored
                    continue
                if isinstance(v, np.ndarray) and not v.flags.writeable:
                    # zero-copy arrow views are read-only; torch wants
                    # ownership for in-place ops (normalize, augment)
                    v = v.copy()
                t = torch.as_tensor(v)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device is not None:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_tf_batches(self, *, batch_size: int = 256, drop_last: bool = False) -> Iterator[Any]:
        """Batches as dicts of tf tensors (reference: data/iterator.py
        iter_tf_batches)."""
        import tensorflow as tf

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last):
            yield {
                k: tf.convert_to_tensor(v) if getattr(v, "dtype", None) is not None
                and v.dtype.kind not in "OUS" else v
                for k, v in batch.items()
            }

    def to_tf(self, feature_columns, label_columns, *, batch_size: int = 256,
              drop_last: bool = False):
        """A `tf.data.Dataset` of (features, labels) dict pairs
        (reference: data/iterator.py to_tf). Column dtypes/shapes are
        inferred from the first batch; single-column sides yield bare
        tensors like the reference."""
        import tensorflow as tf

        feats = [feature_columns] if isinstance(feature_columns, str) else list(feature_columns)
        labels = [label_columns] if isinstance(label_columns, str) else list(label_columns)
        probe = next(self.iter_batches(batch_size=2, batch_format="numpy"))

        def spec(col):
            v = probe[col]
            return tf.TensorSpec(shape=(None,) + v.shape[1:], dtype=tf.as_dtype(v.dtype))

        def side(batch, cols):
            if len(cols) == 1:
                return tf.convert_to_tensor(batch[cols[0]])
            return {c: tf.convert_to_tensor(batch[c]) for c in cols}

        def sig(cols):
            if len(cols) == 1:
                return spec(cols[0])
            return {c: spec(c) for c in cols}

        def gen():
            for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy",
                                           drop_last=drop_last):
                yield side(batch, feats), side(batch, labels)

        return tf.data.Dataset.from_generator(
            gen, output_signature=(sig(feats), sig(labels))
        )

    def streaming_split(self, n: int, *, equal: bool = False) -> List["DataIterator"]:
        """N iterators over disjoint subsets, one per train worker
        (reference: dataset.streaming_split feeding Train). Default:
        round-robin block assignment (zero data movement). equal=True
        re-slices at ROW granularity so every split gets exactly
        total//n rows — SPMD trainers need equal per-worker step counts;
        only boundary blocks are cut, the rest are reused by reference."""
        refs = self._execute_refs()
        if not equal:
            splits = [[r for j, r in enumerate(refs) if j % n == i] for i in builtins.range(n)]
            return [DataIterator(Dataset(s)) for s in splits]

        counts = ray_tpu.get([_block_num_rows.remote(r) for r in refs])
        per = sum(counts) // n
        splits, cur, need = [], [], per
        it = iter([(r, c) for r, c in zip(refs, counts) if c > 0])
        carry = None  # (ref, offset, remaining)
        while len(splits) < n:
            if need == 0:
                splits.append(cur)
                cur, need = [], per
                continue
            if carry is None:
                nxt = next(it, None)
                if nxt is None:
                    splits.append(cur)
                    cur, need = [], per
                    continue
                carry = (nxt[0], 0, nxt[1])
            ref, off, rem = carry
            take = min(rem, need)
            if off == 0 and take == rem:
                cur.append(ref)  # whole block, no copy
            else:
                cur.append(_slice_rows.remote(ref, off, off + take))
            need -= take
            carry = (ref, off + take, rem - take) if rem > take else None
        return [DataIterator(Dataset(s)) for s in splits]

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of equal-length datasets (reference:
        dataset.zip). Distributed: the right side is re-sliced to the
        left side's block row-windows with per-window tasks — the driver
        only moves refs and row counts, never rows."""
        lrefs = self._execute_refs()
        rrefs = other._execute_refs()
        lcounts = ray_tpu.get([_block_num_rows.remote(r) for r in lrefs])
        rcounts = ray_tpu.get([_block_num_rows.remote(r) for r in rrefs])
        if sum(lcounts) != sum(rcounts):
            raise ValueError(
                f"zip requires equal row counts ({sum(lcounts)} vs {sum(rcounts)})"
            )
        out = []
        ri, roff = 0, 0  # cursor into the right side
        for lref, lc in zip(lrefs, lcounts):
            if lc == 0:
                # keep the UNIFIED schema even at zero rows (schema()
                # reads block 0): zip with an empty right slice
                if rrefs:
                    src = rrefs[min(ri, len(rrefs) - 1)]
                    out.append(_zip_blocks.remote(lref, _slice_rows.remote(src, 0, 0)))
                else:
                    out.append(lref)
                continue
            parts, need = [], lc
            while need > 0:
                take = min(need, rcounts[ri] - roff)
                parts.append(
                    rrefs[ri]
                    if take == rcounts[ri] and roff == 0
                    else _slice_rows.remote(rrefs[ri], roff, roff + take)
                )
                roff += take
                need -= take
                if roff == rcounts[ri]:
                    ri, roff = ri + 1, 0
            out.append(_zip_blocks.remote(lref, *parts))
        return Dataset(out)

    def iter_rows(self) -> Iterator[Dict]:
        for ref in self._execute_refs():
            for row in B.block_rows(ray_tpu.get(ref)):
                yield row

    def take(self, n: int = 20) -> List[Dict]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        """Total rows, counted IN TASKS — only integers cross back to
        the driver, never block data (reference: Dataset.count via
        per-block metadata)."""
        refs = self._execute_refs()
        return sum(ray_tpu.get([_block_num_rows.remote(r) for r in refs]))

    def schema(self):
        if not self._block_refs:
            return None
        refs = self._execute_refs()
        if not refs:  # e.g. limit(0): sources exist, plan yields nothing
            return None
        return ray_tpu.get(refs[0]).schema

    def num_blocks(self) -> int:
        # a trailing Exchange repartitions to its M outputs (e.g.
        # repartition(6).num_blocks() == 6 before any execution)
        for op in reversed(self._ops):
            if isinstance(op, L.Exchange):
                return op.M
        return len(self._block_refs)

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    # ------------------------------------------------------------- exports
    def to_pandas(self):
        return B.concat_blocks(ray_tpu.get(self._execute_refs())).to_pandas()

    def to_arrow(self):
        return B.concat_blocks(ray_tpu.get(self._execute_refs()))

    def write_parquet(self, path: str):
        """One parquet file per block, written IN TASKS — block data
        never lands on the driver (same shape as write_tfrecords below;
        reference: Dataset.write_parquet)."""
        import os

        os.makedirs(path, exist_ok=True)
        refs = self._execute_refs()
        ray_tpu.get([
            _write_parquet_block.remote(ref, os.path.join(path, f"part-{i:05d}.parquet"))
            for i, ref in enumerate(refs)
        ])

    def write_csv(self, path: str):
        """One csv file per block, written in tasks (reference:
        Dataset.write_csv)."""
        import os

        os.makedirs(path, exist_ok=True)
        refs = self._execute_refs()
        ray_tpu.get([
            _write_csv_block.remote(ref, os.path.join(path, f"part-{i:05d}.csv"))
            for i, ref in enumerate(refs)
        ])

    def write_tfrecords(self, path: str):
        """One .tfrecord file of tf.train.Example records per block —
        written IN TASKS (block data never lands on the driver;
        reference: Dataset.write_tfrecords)."""
        import os

        os.makedirs(path, exist_ok=True)
        refs = self._execute_refs()
        ray_tpu.get([
            _write_tfrecords_block.remote(ref, os.path.join(path, f"part-{i:05d}.tfrecord"))
            for i, ref in enumerate(refs)
        ])

    def write_webdataset(self, path: str):
        """One .tar webdataset shard per block, written in tasks
        (reference: Dataset.write_webdataset)."""
        import os

        os.makedirs(path, exist_ok=True)
        refs = self._execute_refs()
        ray_tpu.get([
            _write_webdataset_block.remote(ref, os.path.join(path, f"part-{i:05d}.tar"))
            for i, ref in enumerate(refs)
        ])

    def __repr__(self):
        return f"Dataset(num_blocks={len(self._block_refs)}, ops={len(self._ops)})"


class DataIterator:
    """One consumer's streaming view of a dataset split (reference:
    data/iterator.py DataIterator handed out by streaming_split)."""

    def __init__(self, ds: Dataset):
        self._ds = ds

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self._ds.iter_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        return self._ds.iter_torch_batches(**kw)

    def count(self) -> int:
        return self._ds.count()
