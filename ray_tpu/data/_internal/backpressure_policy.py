"""Backpressure policies — pluggable launch admission for the executor.

Equivalent of the reference's backpressure-policy framework (reference:
python/ray/data/_internal/execution/backpressure_policy/ —
ConcurrencyCapBackpressurePolicy + ResourceBudgetBackpressurePolicy,
each answering `can_add_input(op)` from shared resource state). Before
launching a task for a stage, the executor asks EVERY installed policy
`can_launch(stage, usage)`; any refusal defers the launch (the executor
drains an in-flight block to the consumer instead, or sleeps) and is
counted per stage per policy into `Dataset.stats()`.

Two concrete policies:

- `ConcurrencyCapPolicy` — per-stage in-flight window (the previous
  executor's single global budget, split across stages, reframed as a
  policy).
- `ArenaUsagePolicy` — polls shm-arena occupancy
  (`ShmStore.usage()`) and refuses launches while used bytes exceed a
  budget fraction of capacity. Consumption releases blocks (refcount GC),
  usage falls, launches resume — so a pipeline over a dataset far larger
  than the arena holds bounded occupancy instead of racing the LRU
  evictor. A stage with ZERO in-flight tasks is always admitted (progress
  guarantee: occupancy from foreign objects can never wedge the pipeline).
"""
from __future__ import annotations

from typing import Dict, Optional


class ExecUsage:
    """Point-in-time resource snapshot handed to policies.

    `pending_bytes` is the executor's conservative estimate of output
    bytes from launched-but-not-yet-sealed tasks (learned from completed
    task metas) — admission must charge them or a launch burst races
    ahead of what `arena_used_bytes` can see. `unsized_inflight` counts
    a stage's outstanding launches whose output size is still UNKNOWN
    (no completed task has taught the estimate yet): the arena policy
    slow-starts those, since they are invisible to both accounts.
    """

    __slots__ = ("inflight", "arena_used_bytes", "arena_capacity_bytes",
                 "pending_bytes", "unsized_inflight")

    def __init__(
        self,
        inflight: Dict[str, int],
        arena_used_bytes: Optional[int] = None,
        arena_capacity_bytes: Optional[int] = None,
        pending_bytes: int = 0,
        unsized_inflight: Optional[Dict[str, int]] = None,
    ):
        self.inflight = inflight
        self.arena_used_bytes = arena_used_bytes
        self.arena_capacity_bytes = arena_capacity_bytes
        self.pending_bytes = pending_bytes
        self.unsized_inflight = unsized_inflight or {}

    def stage_inflight(self, stage: str) -> int:
        return self.inflight.get(stage, 0)


class BackpressurePolicy:
    """Interface: refuse launches for a stage given current usage."""

    name = "backpressure"

    def can_launch(self, stage: str, usage: ExecUsage) -> bool:
        raise NotImplementedError


class ConcurrencyCapPolicy(BackpressurePolicy):
    """Cap a stage's unconsumed in-flight launches at its window."""

    name = "concurrency_cap"

    def __init__(self, caps: Dict[str, int], default_cap: int = 8):
        self._caps = dict(caps)
        self._default = default_cap

    def cap(self, stage: str) -> int:
        return self._caps.get(stage, self._default)

    def can_launch(self, stage: str, usage: ExecUsage) -> bool:
        return usage.stage_inflight(stage) < self.cap(stage)


class ArenaUsagePolicy(BackpressurePolicy):
    """Throttle launches while shm-arena occupancy exceeds the budget.

    budget = `budget_bytes` if given, else `fraction` x arena capacity.
    Admission charges sealed bytes PLUS the executor's pending-output
    estimate, and slow-starts a stage (≤ `slow_start` outstanding
    launches) until a completed task has taught its output size — both
    guards close the launch-vs-seal race in which a full window of
    launches overshoots the budget before any sealed byte is visible.
    """

    name = "arena_usage"

    def __init__(self, fraction: float = 0.75, budget_bytes: Optional[int] = None,
                 slow_start: int = 2):
        self.fraction = fraction
        self.budget_bytes = budget_bytes
        self.slow_start = slow_start

    def budget(self, capacity: int) -> int:
        if self.budget_bytes is not None:
            return self.budget_bytes
        return int(self.fraction * capacity)

    def can_launch(self, stage: str, usage: ExecUsage) -> bool:
        if usage.arena_capacity_bytes is None:
            return True  # no arena visible from this process: stand down
        if usage.stage_inflight(stage) == 0:
            return True  # progress guarantee
        if usage.unsized_inflight.get(stage, 0) >= self.slow_start:
            return False  # unknown output size: probe before bursting
        committed = usage.arena_used_bytes + usage.pending_bytes
        return committed <= self.budget(usage.arena_capacity_bytes)
