"""Typed logical operators — the nodes of a Dataset's logical plan.

Equivalent of the reference's logical operator tree (reference:
python/ray/data/_internal/logical/operators/map_operator.py etc. — there
transformations build `LogicalOperator` nodes that the planner lowers to
physical operators). Here each Dataset holds a linear chain of these
objects; the optimizer (`optimizer.py`) rewrites the chain (pushdown,
fusion) and the executor lowers it to task / actor-pool stages.

Every operator knows how to apply itself to one Arrow block
(`apply_block`), so a fused run of operators executes as ONE remote task
per block — the single dispatch point shared by the streaming executor,
the shuffle map stages and the preprocessor fit tasks. Operators are
cloudpickled into the object store once per execution and fanned out to
tasks by ref.

Legacy `(kind, fn, kw)` tuples (the pre-plan representation, still a
valid input to `_apply_ops_local`) are upgraded via `as_op`.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


def _callable_name(fn) -> str:
    n = getattr(fn, "__name__", None) or type(fn).__name__
    return n if n != "<lambda>" else "fn"


class LogicalOp:
    """One node of the logical plan.

    kind: stable string id (matches the legacy tuple kinds).
    fusable: may join a fused one-task-per-block run.
    limit_pushdown_safe: a Limit may hop left past this op — requires
    BOTH that the op preserves row count AND that its fn never sees
    beyond the row it produces (batch-level aggregates would change
    under reordering).
    """

    kind: str = "?"
    fusable: bool = True
    limit_pushdown_safe: bool = False

    @property
    def name(self) -> str:
        return type(self).__name__

    def apply_block(self, blk):
        raise NotImplementedError

    def __repr__(self):
        return self.name


class MapRows(LogicalOp):
    kind = "map"
    limit_pushdown_safe = True  # per-row fn

    def __init__(self, fn: Callable[[Dict], Dict]):
        self.fn = fn

    @property
    def name(self):
        return f"Map({_callable_name(self.fn)})"

    def apply_block(self, blk):
        from ray_tpu.data import block as B

        return B.to_block([self.fn(r) for r in B.block_rows(blk)])


class MapBatches(LogicalOp):
    kind = "map_batches"

    def __init__(self, fn, *, batch_format: str = "numpy",
                 compute: Optional[str] = None, num_actors: int = 2,
                 fn_constructor_args=None, fn_constructor_kwargs=None,
                 ray_actor_options: Optional[Dict] = None):
        self.fn = fn
        self.batch_format = batch_format
        self.compute = compute
        self.num_actors = num_actors
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs
        self.ray_actor_options = ray_actor_options

    @property
    def is_actor_pool(self) -> bool:
        return self.compute == "actors"

    @property
    def fusable(self) -> bool:  # type: ignore[override]
        return not self.is_actor_pool

    @property
    def name(self):
        tag = "ActorMapBatches" if self.is_actor_pool else "MapBatches"
        return f"{tag}({_callable_name(self.fn)})"

    def apply_block(self, blk):
        from ray_tpu.data import block as B

        out = self.fn(B.block_to_batch(blk, self.batch_format))
        return B.batch_to_block(out)


class FlatMap(LogicalOp):
    kind = "flat_map"

    def __init__(self, fn):
        self.fn = fn

    @property
    def name(self):
        return f"FlatMap({_callable_name(self.fn)})"

    def apply_block(self, blk):
        from ray_tpu.data import block as B

        rows = []
        for r in B.block_rows(blk):
            rows.extend(self.fn(r))
        return B.to_block(rows)


class Filter(LogicalOp):
    kind = "filter"

    def __init__(self, fn):
        self.fn = fn

    @property
    def name(self):
        return f"Filter({_callable_name(self.fn)})"

    def apply_block(self, blk):
        from ray_tpu.data import block as B

        return B.to_block([r for r in B.block_rows(blk) if self.fn(r)])


class AddColumn(LogicalOp):
    kind = "add_column"
    # row count IS preserved, but the column fn receives the whole block
    # as a pandas batch — a batch-level aggregate (df.x - df.x.mean())
    # would see only the surviving rows if a Limit hopped past it, so
    # limit pushdown must not reorder around this op

    def __init__(self, col: str, fn):
        self.col = col
        self.fn = fn

    @property
    def name(self):
        return f"AddColumn({self.col})"

    def apply_block(self, blk):
        import pyarrow as pa

        from ray_tpu.data import block as B

        vals = self.fn(B.block_to_batch(blk, "pandas"))
        return blk.append_column(self.col, pa.array(list(vals)))


class DropColumns(LogicalOp):
    kind = "drop_columns"
    limit_pushdown_safe = True

    def __init__(self, cols: List[str]):
        self.cols = list(cols)

    @property
    def name(self):
        return f"DropColumns({','.join(self.cols)})"

    def apply_block(self, blk):
        return blk.drop_columns(self.cols)


class SelectColumns(LogicalOp):
    kind = "select_columns"
    limit_pushdown_safe = True

    def __init__(self, cols: List[str]):
        self.cols = list(cols)

    @property
    def name(self):
        return f"SelectColumns({','.join(self.cols)})"

    def apply_block(self, blk):
        return blk.select(self.cols)


class RenameColumns(LogicalOp):
    kind = "rename_columns"
    limit_pushdown_safe = True

    def __init__(self, mapping: Dict[str, str]):
        self.mapping = dict(mapping)

    @property
    def name(self):
        return "RenameColumns"

    def apply_block(self, blk):
        return blk.rename_columns([self.mapping.get(c, c) for c in blk.column_names])


class Limit(LogicalOp):
    """Global first-n-rows. NOT fusable: the executor enforces the global
    budget (stop pulling upstream, slice the boundary block); shuffle
    paths must resolve it before shipping the chain to per-block map
    tasks (Dataset._exchange_inputs). apply_block is only the per-block
    UPPER BOUND n-rows slice, never the whole semantics."""

    kind = "limit"
    fusable = False

    def __init__(self, n: int):
        self.n = int(n)

    @property
    def name(self):
        return f"Limit[{self.n}]"

    def apply_block(self, blk):
        return blk.slice(0, min(self.n, blk.num_rows))


class Exchange(LogicalOp):
    """All-to-all repartition barrier — the streaming shuffle exchange
    (data/_internal/exchange.py). NOT fusable as a narrow op: the planner
    lowers it to an ExchangeStage whose mappers push partition chunks to
    reducer actors over shm rings (put/get refs across nodes) as blocks
    arrive, and whose reducers buffer chunks heap-side and merge each
    partition at finalize — no N×M part-ref materialization (the
    seed-era 2-stage shuffle in data/_shuffle.py survives only as the
    legacy/cross-node fallback path).

    mode: "random" (shuffle), "range" (sort), "chunk" (repartition),
    "hash" (groupby placement). `arg` is per-mode (range boundaries /
    hash key), `per_map_args` per-mapper (chunk offsets), `reduce_fn` an
    optional post-merge transform applied reducer-side (groupby
    aggregates there instead of rematerializing every partition)."""

    kind = "exchange"
    fusable = False

    def __init__(self, mode: str, num_partitions: int, arg=None, reduce_arg=None,
                 seed: Optional[int] = None, per_map_args: Optional[List] = None,
                 reduce_fn: Optional[Callable] = None):
        if mode not in ("random", "range", "chunk", "hash"):
            raise ValueError(f"unknown exchange mode {mode}")
        self.mode = mode
        self.M = int(num_partitions)
        self.arg = arg
        self.reduce_arg = reduce_arg
        self.seed = seed
        self.per_map_args = per_map_args
        self.reduce_fn = reduce_fn

    @property
    def name(self):
        return f"Exchange[{self.mode}]"

    def apply_block(self, blk):
        raise RuntimeError(
            "Exchange is a barrier operator — it cannot apply per block; "
            "execute through the plan (materialize/iter_batches)"
        )


_LEGACY = {
    "map": lambda fn, kw: MapRows(fn),
    "map_batches": lambda fn, kw: MapBatches(
        fn,
        batch_format=kw.get("batch_format", "numpy"),
        compute=kw.get("compute"),
        num_actors=int(kw.get("num_actors", 2)),
        fn_constructor_args=kw.get("fn_constructor_args"),
        fn_constructor_kwargs=kw.get("fn_constructor_kwargs"),
        ray_actor_options=kw.get("ray_actor_options"),
    ),
    "flat_map": lambda fn, kw: FlatMap(fn),
    "filter": lambda fn, kw: Filter(fn),
    "add_column": lambda fn, kw: AddColumn(fn[0], fn[1]),
    "drop_columns": lambda fn, kw: DropColumns(fn),
    "select_columns": lambda fn, kw: SelectColumns(fn),
    "rename_columns": lambda fn, kw: RenameColumns(fn),
}


def as_op(op) -> LogicalOp:
    """Upgrade a legacy (kind, fn, kw) tuple to a LogicalOp; pass typed
    operators through."""
    if isinstance(op, LogicalOp):
        return op
    kind, fn, kw = op
    try:
        return _LEGACY[kind](fn, kw or {})
    except KeyError:
        raise ValueError(f"unknown op {kind}") from None


def apply_ops(blk, ops) -> Any:
    """Run a chain of logical ops (or legacy tuples) over one block."""
    for op in ops or []:
        blk = as_op(op).apply_block(blk)
    return blk
