"""Streaming shuffle exchange — push-based all-to-all on the ring substrate.

The seed-era shuffle (`data/_shuffle.py`) materializes N×M part refs
through the object store and only then reduces. This operator is the
Magnet/Exoshuffle shape instead (PAPERS.md [5][14]): mappers PUSH
partition chunks to reducer actors *as they are produced* (no N×M
part-ref materialization), and the whole exchange is planned by the
optimizer as a first-class stage whose launches ride the executor's
backpressure policies — a shuffle larger than the arena budget streams
instead of OOMing. The bounded resource is ARENA occupancy: chunks
bypass the arena on the ring and outputs seal into it only as the
arena policy admits finalizes. Reducer-side, chunks accumulate in the
reducer's private heap until `finalize(j)` merges that partition — so
reducer RSS scales with the partitions it owns (dataset/R), not with
the arena budget.

Transport matrix (per mapper-task × reducer pair):

  colocated (reducer ring openable on this node) → `RingChannel`
      chunks move through one multi-producer /dev/shm byte ring per
      (reducer, exchange); they never touch the shm arena at all.
      Ring-full blocks the writer (slow-reader backpressure, counted).
  cross-node / ring unavailable / record > ring  → put/get fallback
      the chunk rides a normal actor call (`add_part`), i.e. the object
      plane — the same path `_shuffle.py`'s hierarchical fan-in uses.

Completion protocol: every mapper sends exactly one DONE marker per
reducer (ring record, or an acked `mapper_done` call) AFTER all its
chunks for that reducer are delivered (fallback chunks are acked before
the marker ships, so DONE really means "everything of mine is there").
`finalize(j)` waits until all `n_mappers` markers arrived, merges the
partition's chunks in deterministic (mapper, seq) order, applies the
mode finalization (permute / sort / optional reduce_fn) and returns the
block — launched per partition by the executor, gated by the arena
policy, so outputs seal into the arena only as the consumer drains them.

Reducer actors are pooled per driver (spawning R processes per shuffle
would dominate small exchanges); per-exchange state is keyed by a random
exchange id, and `end_exchange` unlinks the rings so nothing litters
/dev/shm between shuffles.
"""
from __future__ import annotations

import collections
import os
import struct
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data._shuffle import finalize_partition, partition_block

# ring record: kind, partition j, mapper idx, per-(mapper, partition) seq.
# Format string + calcsize (not a struct.Struct instance): the reducer
# class and mapper function are cloudpickled BY VALUE into the function
# table (the @remote wrapper shadows their module names), and a Struct
# object in their globals is not picklable. Padded to 24 bytes so the
# wire payload (whose oob buffers are 64-aligned RELATIVE to it) stays
# 8-aligned absolute — arrow rejects/warns on misaligned buffer views.
_REC_FMT = "<BIIQ7x"
_REC_SIZE = struct.calcsize(_REC_FMT)
K_DATA, K_DONE, K_WAKE = 1, 2, 3

_FINALIZE_TIMEOUT_S = 300.0


def _apply_mapper_ops(blk, ops):
    """Apply the fused upstream run inside the mapper, timing per op
    (the chain arrives via the ONE spec put — never re-pickled per
    chunk)."""
    from ray_tpu.data._internal.logical_ops import as_op

    per_op: Dict[str, float] = {}
    for op in ops or []:
        o = as_op(op)
        ta = time.perf_counter()
        blk = o.apply_block(blk)
        per_op[o.name] = per_op.get(o.name, 0.0) + time.perf_counter() - ta
    return blk, per_op


def _iter_chunks(tbl, chunk_bytes: int):
    """Row-slice a partition part into ring-sized chunks. Empty parts
    still yield once: the (schema-carrying) empty table is what keeps
    empty partitions schema-stable after the merge."""
    if tbl.num_rows == 0 or tbl.nbytes <= chunk_bytes:
        yield tbl
        return
    n_chunks = -(-tbl.nbytes // chunk_bytes)
    per = max(1, -(-tbl.num_rows // n_chunks))
    for off in range(0, tbl.num_rows, per):
        yield tbl.slice(off, per)


def _pack_data_record(j: int, midx: int, seq: int, tbl, capacity=None):
    """One ring record: header + the object-plane wire format of the
    chunk. The table's arrow buffers travel OUT-OF-BAND (pickle5 buffer
    callbacks) and land via the serializer's native bulk copy — an
    inline-buffer pickle of a 4 MiB table measured ~100x slower because
    it byte-copies every buffer through the pickle stream. One
    allocation, no header/payload concat. Returns None when the record
    could never fit a ring of `capacity` — decided from the size alone,
    BEFORE the payload copy, so an oversize chunk costs no wasted
    memcpy on its way to the object-plane fallback."""
    from ray_tpu._private import serialization
    from ray_tpu.experimental.channel import RingChannel

    pickled, buffers, _ = serialization.serialize(tbl)
    total = serialization.serialized_size(pickled, buffers)
    if capacity is not None and RingChannel._rec_size(_REC_SIZE + total) > capacity:
        return None
    rec = bytearray(_REC_SIZE + total)
    struct.pack_into(_REC_FMT, rec, 0, K_DATA, j, midx, seq)
    serialization.write_to(memoryview(rec)[_REC_SIZE:], pickled, buffers)
    return rec


def _unpack_data_record(rec) -> Any:
    """Decode a ring record ZERO-COPY: the returned table's buffers
    alias the record bytes (which the table keeps alive), so the merge
    path pays no decode copy — arrow's concat is chunked/zero-copy and
    only the mode finalization (permute/sort) materializes rows."""
    from ray_tpu._private import serialization

    return serialization.from_buffer(memoryview(rec)[_REC_SIZE:], zero_copy=True)


@ray_tpu.remote
class _ExchangeReducer:
    """Pooled reducer endpoint: owns one multi-producer ring + one drain
    thread per active exchange, merges chunks per partition, finalizes
    on demand. Thread-safe (the actor runs with max_concurrency > 1 so
    `finalize`'s wait cannot block fallback `add_part` deliveries)."""

    def __init__(self):
        self._ex: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    # -- lifecycle per exchange -----------------------------------------
    def begin_exchange(self, xid: str, parts: List[int], ring_capacity: int,
                       mode: str, reduce_arg, seed: int, reduce_fn) -> Dict[str, Any]:
        from ray_tpu._private.worker import get_global_core

        st: Dict[str, Any] = {
            "parts": {j: [] for j in parts},
            "done": set(),
            "cv": threading.Condition(),
            "ring": None,
            "drain": None,
            "closed": False,
            "mode": mode,
            "reduce_arg": reduce_arg,
            "seed": seed,
            "reduce_fn": reduce_fn,
            "ring_bytes": 0,
            "fallback_bytes": 0,
            "chunks": 0,
            "counters_reported": False,
        }
        path = None
        if ring_capacity:
            try:
                from ray_tpu.experimental.channel import RingChannel

                # multi_producer also on the CREATE side: end_exchange's
                # K_WAKE write must take the same cross-process fcntl
                # lock as the mappers' writes — an aborted exchange tears
                # down while mappers may still be mid-push, and a native
                # single-producer handle would race their head updates
                st["ring"] = RingChannel.create(
                    f"xch_{xid[:12]}", ring_capacity, multi_producer=True
                )
                path = st["ring"].path
                st["drain"] = threading.Thread(
                    target=self._drain_loop, args=(st,), daemon=True,
                    name=f"xch-drain-{xid[:8]}",
                )
                st["drain"].start()
            except Exception:
                st["ring"] = None
                path = None
        with self._lock:
            self._ex[xid] = st
        core = get_global_core()
        return {"node_id": core.node_id, "path": path}

    def end_exchange(self, xid: str) -> bool:
        with self._lock:
            st = self._ex.pop(xid, None)
        if st is None:
            return False
        st["closed"] = True
        if st["ring"] is not None:
            try:
                # wake the drain thread NOW: it re-checks `closed` only
                # when read() returns, so without a nudge every shuffle
                # pays up to a full 0.2s read-timeout at teardown
                st["ring"].write(struct.pack(_REC_FMT, K_WAKE, 0, 0, 0), timeout=0)
            except Exception:
                pass  # ring full/torn: the read timeout covers exit
        if st["drain"] is not None:
            st["drain"].join(timeout=5)
        if st["ring"] is not None:
            st["ring"].unlink()
        return True

    # -- ring ingest ----------------------------------------------------
    def _drain_loop(self, st):
        from ray_tpu.experimental.channel import ChannelTimeoutError

        ring = st["ring"]
        while not st["closed"]:
            try:
                rec = ring.read(timeout=0.2)
            except ChannelTimeoutError:
                continue
            except Exception:
                return  # ring torn down under us: exchange is over
            kind, j, midx, seq = struct.unpack_from(_REC_FMT, rec, 0)
            with st["cv"]:
                if kind == K_DATA:
                    # decode deferred to finalize: the drain thread only
                    # appends, so a fast mapper burst never backs up the
                    # ring behind arrow work
                    st["parts"].setdefault(j, []).append((midx, seq, rec))
                    st["ring_bytes"] += len(rec) - _REC_SIZE
                    st["chunks"] += 1
                elif kind == K_DONE:
                    st["done"].add(midx)
                    st["cv"].notify_all()
                # K_WAKE: teardown nudge — loop back to the closed check

    # -- fallback ingest (cross-node / oversize / ring-less) -------------
    def add_part(self, xid: str, j: int, midx: int, seq: int, tbl) -> bool:
        with self._lock:
            st = self._ex.get(xid)
        if st is None:
            raise RuntimeError(f"exchange {xid} is not active on this reducer")
        with st["cv"]:
            st["parts"].setdefault(j, []).append((midx, seq, tbl))
            st["fallback_bytes"] += tbl.nbytes
            st["chunks"] += 1
        return True

    def mapper_done(self, xid: str, midx: int) -> bool:
        with self._lock:
            st = self._ex.get(xid)
        if st is None:
            raise RuntimeError(f"exchange {xid} is not active on this reducer")
        with st["cv"]:
            st["done"].add(midx)
            st["cv"].notify_all()
        return True

    # -- output ---------------------------------------------------------
    def finalize(self, xid: str, j: int, n_mappers: int):
        with self._lock:
            st = self._ex.get(xid)
        if st is None:
            raise RuntimeError(f"exchange {xid} is not active on this reducer")
        t0 = time.perf_counter()
        deadline = time.monotonic() + _FINALIZE_TIMEOUT_S
        with st["cv"]:
            while len(st["done"]) < n_mappers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"exchange {xid} partition {j}: only {len(st['done'])}"
                        f"/{n_mappers} mappers reported done within "
                        f"{_FINALIZE_TIMEOUT_S:.0f}s (mapper failure or lost ring?)"
                    )
                st["cv"].wait(timeout=min(remaining, 1.0))
            entries = st["parts"].pop(j, [])
            report = not st["counters_reported"]
            st["counters_reported"] = True
            ring_bytes, fb_bytes, chunks = st["ring_bytes"], st["fallback_bytes"], st["chunks"]
        # deterministic merge order — chunks arrive interleaved across
        # mappers, but (mapper idx, seq) reconstructs submission order,
        # which is what makes seeded shuffles reproducible
        entries.sort(key=lambda e: (e[0], e[1]))
        tables = [
            _unpack_data_record(e[2]) if isinstance(e[2], (bytes, bytearray))
            else e[2]
            for e in entries
        ]
        blk = B.concat_blocks(tables) if tables else B.to_block([])
        rows_in, bytes_in = blk.num_rows, blk.nbytes
        blk = finalize_partition(blk, st["mode"], st["reduce_arg"], st["seed"] + 31 * j + 7)
        if st["reduce_fn"] is not None:
            blk = st["reduce_fn"](blk)
        meta = {
            "rows_in": rows_in,
            "rows_out": blk.num_rows,
            "bytes_in": bytes_in,
            "bytes_out": blk.nbytes,
            "task_s": time.perf_counter() - t0,
            "per_op_s": {},
            # per-exchange transport counters ride the FIRST finalize of
            # this reducer only (they are reducer-wide; attaching them to
            # every partition would multiply them in the stats sum)
            "exchange_ring_bytes": ring_bytes if report else 0,
            "exchange_fallback_bytes": fb_bytes if report else 0,
            "exchange_chunks": chunks if report else 0,
        }
        return blk, meta


@ray_tpu.remote
def _exchange_map(blk, spec, midx: int):
    """One mapper: apply the fused upstream ops, partition the block,
    push every partition's chunks to its reducer — ring when colocated,
    acked actor-call fallback otherwise — then mark this mapper done on
    every reducer. Returns ONLY a meta dict (the data already moved)."""
    from ray_tpu._private.worker import get_global_core
    from ray_tpu.experimental.channel import RingChannel, RingFullError

    t0 = time.perf_counter()
    rows_in, bytes_in = blk.num_rows, blk.nbytes
    blk, per_op = _apply_mapper_ops(blk, spec.get("ops"))
    mode, M = spec["mode"], spec["M"]
    pm = spec.get("per_map_args")
    arg = pm[midx] if pm is not None else spec.get("arg")
    parts = partition_block(blk, mode, M, arg, spec["seed"] + 17 * midx + 1)
    node_id = get_global_core().node_id
    ring_bytes = fallback_bytes = chunks = throttled = 0
    for rinfo, handle in zip(spec["reducers"], spec["handles"]):
        ring = None
        if rinfo["path"] and rinfo["node_id"] == node_id:
            try:
                # opening the reducer's /dev/shm path IS the colocation
                # check (same contract as the direct actor transport)
                ring = RingChannel.open(rinfo["path"], multi_producer=True)
            except Exception:
                ring = None
        try:
            pending = []
            for j in rinfo["parts"]:
                seq = 0
                for chunk in _iter_chunks(parts[j], spec["chunk_bytes"]):
                    sent = False
                    if ring is not None:
                        rec = _pack_data_record(j, midx, seq, chunk, capacity=ring.capacity)
                        if rec is not None:
                            try:
                                ring.write(rec, timeout=0)
                                sent = True
                            except RingFullError:
                                # slow-reader backpressure: count the
                                # throttle, then block until there's room
                                throttled += 1
                                ring.write(rec, timeout=120.0)
                                sent = True
                        # else: record can never fit — object-plane fallback
                    if sent:
                        ring_bytes += len(rec) - _REC_SIZE
                    else:
                        pending.append(handle.add_part.remote(spec["xid"], j, midx, seq, chunk))
                        fallback_bytes += chunk.nbytes
                    chunks += 1
                    seq += 1
            if pending:
                # fallback chunks must be RECORDED before the done marker
                # ships (get: a failed delivery fails this mapper loudly)
                ray_tpu.get(pending)
            if ring is not None:
                ring.write(struct.pack(_REC_FMT, K_DONE, 0, midx, 0), timeout=120.0)
            else:
                ray_tpu.get(handle.mapper_done.remote(spec["xid"], midx))
        finally:
            if ring is not None:
                ring.close()
    return {
        "rows_in": rows_in,
        "rows_out": sum(p.num_rows for p in parts),
        "bytes_in": bytes_in,
        # mapper output bytes that actually land in the ARENA: only the
        # fallback chunks (ring bytes bypass the object plane entirely).
        # The executor's pending-output estimate keys off this, so ring
        # transport doesn't phantom-charge the arena budget.
        "bytes_out": fallback_bytes,
        "task_s": time.perf_counter() - t0,
        "per_op_s": per_op,
        "exchange_ring_bytes": ring_bytes,
        "exchange_fallback_bytes": fallback_bytes,
        "exchange_chunks": chunks,
        "exchange_ring_throttled": throttled,
    }


# ---------------------------------------------------------------- driver side

_POOL: Dict[str, List[Any]] = {}  # core worker_id -> reducer handles


def _reducer_pool(n: int) -> List[Any]:
    """Per-driver pool of reducer actors (spawned lazily, reused across
    exchanges — an actor spawn per shuffle would dominate small ones)."""
    from ray_tpu._private.worker import get_global_core

    key = get_global_core().worker_id
    for k in list(_POOL):
        if k != key:
            _POOL.pop(k, None)  # stale pool from a previous init cycle
    handles = _POOL.setdefault(key, [])
    while len(handles) < n:
        handles.append(_ExchangeReducer.options(max_concurrency=8).remote())
    return handles[:n]


def _begin(xid: str, op, owned: List[List[int]], ring_cap: int) -> tuple:
    """Spawn/reuse reducers and open the exchange on each; one retry
    with a fresh pool when a pooled reducer died since the last use."""
    from ray_tpu._private.worker import get_global_core

    seed = 0 if op.seed is None else op.seed
    for attempt in range(2):
        handles = _reducer_pool(len(owned))
        try:
            infos = ray_tpu.get([
                h.begin_exchange.remote(xid, owned[r], ring_cap, op.mode,
                                        op.reduce_arg, seed, op.reduce_fn)
                for r, h in enumerate(handles)
            ])
            return handles, infos
        except Exception:
            if attempt:
                raise
            _POOL.pop(get_global_core().worker_id, None)
    raise RuntimeError("unreachable")


def _reap(pending: List[Any], state, name: str, timeout: float) -> List[Any]:
    """Consume any resolved mapper metas from the stage window."""
    if not pending:
        return pending
    try:
        ready, rest = ray_tpu.wait(pending, num_returns=len(pending), timeout=timeout)
    except Exception:
        return pending
    for _ in ready:
        state.consumed(name)
    return rest


def _map_phase(upstream: Iterator, spec_ref, stage, state) -> tuple:
    """Launch one mapper task per upstream block, policy-gated; returns
    (mapper count, total bytes pushed) once every mapper has COMPLETED
    (reducers need the exact count before any partition can finalize;
    the byte total seeds the finalize stage's output-size estimate)."""
    name = stage.map_name
    pending: List[Any] = []
    launched: List[Any] = []
    n = 0
    for ref in upstream:
        while not state.admit(name):
            got = _reap(pending, state, name, timeout=0)
            if got is pending or len(got) == len(pending):
                time.sleep(state.poll_interval)
            pending = got
        meta_ref = _exchange_map.remote(ref, spec_ref, n)
        state.launched(name, meta_ref)
        state.stats.add_meta(name, meta_ref)
        pending.append(meta_ref)
        launched.append(meta_ref)
        n += 1
    while pending:
        pending = _reap(pending, state, name, timeout=0.05)
    total_pushed = 0
    if launched:
        # tiny meta dicts, ONE bulk fetch — this is the error barrier: a
        # failed mapper raises here instead of wedging finalize() for
        # its full done-marker timeout
        for m in ray_tpu.get(launched):
            total_pushed += m.get("exchange_ring_bytes", 0) \
                + m.get("exchange_fallback_bytes", 0)
    return n, total_pushed


def _reduce_phase(xid: str, handles, M: int, n_mappers: int, stage, state) -> Iterator:
    """Finalize partitions one by one, gated by the backpressure
    policies — outputs seal into the arena only as the consumer drains,
    which is what keeps a larger-than-arena shuffle inside its budget.
    No driver-side get here: finalize results stream to the consumer as
    refs."""
    from ray_tpu.data._executor import _gated

    name = stage.name
    R = len(handles)
    fin = [h.finalize.options(num_returns=2) for h in handles]
    buf: collections.deque = collections.deque()
    for j in range(M):
        yield from _gated(state, name, buf)
        out, meta = fin[j % R].remote(xid, j, n_mappers)
        state.launched(name, meta)
        state.stats.add_meta(name, meta)
        buf.append(out)
    while buf:
        state.consumed(name)
        yield buf.popleft()


def run_exchange_stage(upstream: Iterator, stage, state, ctx) -> Iterator:
    """Execute one ExchangeStage inside the streaming executor."""
    op = stage.op
    M = op.M
    xid = os.urandom(8).hex()
    R = max(1, min(M, int(ctx.exchange_num_reducers)))
    owned = [list(range(r, M, R)) for r in range(R)]
    ring_cap = int(ctx.exchange_ring_capacity) if ctx.exchange_use_rings else 0
    handles, infos = _begin(xid, op, owned, ring_cap)
    spec = {
        "xid": xid,
        "mode": op.mode,
        "M": M,
        "arg": op.arg,
        "seed": 0 if op.seed is None else op.seed,
        "per_map_args": op.per_map_args,
        "chunk_bytes": int(ctx.exchange_chunk_bytes),
        "ops": stage.mapper_ops,
        "reducers": [
            {"parts": owned[r], "path": infos[r]["path"], "node_id": infos[r]["node_id"]}
            for r in range(R)
        ],
        "handles": handles,
    }
    # ONE put carries the whole exchange plan (ops chain included) to
    # every mapper — nothing is re-pickled per block or per chunk
    spec_ref = ray_tpu.put(spec)
    # ring-borne mapper output never lands in the arena: seed the size
    # estimate so the arena policy's unsized slow-start (meant for
    # arena-writing stages) does not serialize mapper launches while the
    # first meta is still in flight
    state.seed_estimate(stage.map_name, 0.0)
    try:
        n_mappers, total_pushed = _map_phase(upstream, spec_ref, stage, state)
        # finalize outputs DO seal into the arena at ~total/M bytes each;
        # seeding that honest size skips the unsized probe stall AND
        # gives admission a real number to charge per in-flight finalize
        state.seed_estimate(stage.name, total_pushed / max(1, M))
        yield from _reduce_phase(xid, handles, M, n_mappers, stage, state)
    finally:
        try:
            done = [h.end_exchange.remote(xid) for h in handles]
            ray_tpu.wait(done, num_returns=len(done), timeout=30)
        except Exception:
            pass
