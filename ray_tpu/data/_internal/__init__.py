"""ray_tpu.data._internal — data-execution internals.

Equivalent of the reference's `python/ray/data/_internal/`: the logical
plan (`logical_ops.py`), the plan optimizer (`optimizer.py` — operator
fusion + limit/projection pushdown), the backpressure-policy framework
(`backpressure_policy.py`) and execution stats (`stats.py`). The
streaming executor itself lives in `ray_tpu/data/_executor.py` and
plans over these pieces.
"""
