"""Execution stats — per-operator timings, task counts and throttles.

Equivalent of the reference's `Dataset.stats()` machinery (reference:
python/ray/data/_internal/stats.py — DatasetStats aggregating per-block
metadata from task-side timers into a per-operator summary string). Each
fused task / actor call returns a second small object (its meta dict:
rows/bytes in/out, task wall time, a per-operator time breakdown inside
the fused run) via `num_returns=2`, so only integers and floats ever
cross back to the driver. The driver-side `StatsBuilder` accumulates
launch counts and backpressure throttles as the executor runs, then
`build()` resolves the meta refs into an immutable `DatasetStats` —
rendered as a human-readable report (str) and a plain dict
(`to_dict()`) for programmatic assertions.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.util.metrics import metric_singletons as _metric_singletons


def _data_metrics_factory():
    """Singletons bridging Dataset.stats() into the metrics pipeline
    (dashboard /metrics): per-operator task/row/byte counters and
    per-policy throttle counts."""
    from ray_tpu.util import metrics

    return dict(
        tasks=metrics.Counter(
            "ray_tpu_data_tasks_total", "data tasks launched",
            tag_keys=("operator",)),
        rows_out=metrics.Counter(
            "ray_tpu_data_rows_out_total", "rows produced",
            tag_keys=("operator",)),
        bytes_out=metrics.Counter(
            "ray_tpu_data_bytes_out_total", "bytes produced",
            tag_keys=("operator",)),
        task_time=metrics.Counter(
            "ray_tpu_data_task_time_s_total", "task wall time",
            tag_keys=("operator",)),
        throttles=metrics.Counter(
            "ray_tpu_data_backpressure_throttles_total",
            "launch refusals by policy",
            tag_keys=("operator", "policy")),
        exchange_ring_bytes=metrics.Counter(
            "ray_tpu_data_exchange_ring_bytes_total",
            "exchange bytes moved over shm rings",
            tag_keys=("operator",)),
        exchange_fallback_bytes=metrics.Counter(
            "ray_tpu_data_exchange_fallback_bytes_total",
            "exchange bytes moved via put/get fallback",
            tag_keys=("operator",)),
        exchange_chunks=metrics.Counter(
            "ray_tpu_data_exchange_chunks_total",
            "exchange chunks streamed",
            tag_keys=("operator",)),
        exchange_ring_throttled=metrics.Counter(
            "ray_tpu_data_exchange_ring_throttles_total",
            "mapper writes that hit a full ring (slow-reader backpressure)",
            tag_keys=("operator",)),
    )


# optional per-exchange counters carried in task metas (mapper AND
# reducer sides both report; sums surface per stage in stats() and
# bridge into the metrics pipeline like the other operator counters)
_EXCHANGE_KEYS = (
    "exchange_ring_bytes",
    "exchange_fallback_bytes",
    "exchange_chunks",
    "exchange_ring_throttled",
)


_data_metrics = _metric_singletons(_data_metrics_factory)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


class DatasetStats:
    """Immutable per-execution stats: ordered {stage name: metrics}."""

    def __init__(self, operators: "Dict[str, Dict[str, Any]]",
                 total_wall_s: float, executed: bool = True):
        self.operators = operators
        self.total_wall_s = total_wall_s
        self.executed = executed

    def to_dict(self) -> Dict[str, Any]:
        throttles: Dict[str, int] = {}
        for m in self.operators.values():
            for pol, n in m.get("throttled", {}).items():
                throttles[pol] = throttles.get(pol, 0) + n
        return {
            "executed": self.executed,
            "operators": {k: dict(v) for k, v in self.operators.items()},
            "total_wall_s": self.total_wall_s,
            "total_tasks": sum(m.get("tasks", 0) for m in self.operators.values()),
            "backpressure_throttles": throttles,
        }

    def summary(self) -> str:
        if not self.executed:
            return "Dataset stats: not executed yet (iterate or materialize first)"
        lines = [f"Dataset execution stats ({self.total_wall_s * 1e3:.0f}ms total):"]
        for i, (name, m) in enumerate(self.operators.items()):
            parts = [f"{m.get('tasks', 0)} tasks"]
            if m.get("task_s") is not None:
                parts.append(f"{m['task_s'] * 1e3:.0f}ms task time")
            if m.get("rows_in") is not None:
                parts.append(f"{m['rows_in']}->{m['rows_out']} rows")
            elif m.get("rows_out") is not None:
                # limit stages count rows driver-side only (no task meta)
                parts.append(f"{m['rows_out']} rows out")
            if m.get("bytes_in") is not None:
                parts.append(f"{_fmt_bytes(m['bytes_in'])}->{_fmt_bytes(m['bytes_out'])}")
            if m.get("throttled"):
                th = ", ".join(f"{k}: {v}" for k, v in m["throttled"].items())
                parts.append(f"throttled({th})")
            if m.get("exchange_chunks"):
                parts.append(
                    f"exchange({_fmt_bytes(m.get('exchange_ring_bytes', 0))} ring, "
                    f"{_fmt_bytes(m.get('exchange_fallback_bytes', 0))} fallback, "
                    f"{m['exchange_chunks']} chunks, "
                    f"{m.get('exchange_ring_throttled', 0)} ring-throttles)"
                )
            lines.append(f"  Operator {i} {name}: " + ", ".join(parts))
            for op_name, s in (m.get("per_op_s") or {}).items():
                lines.append(f"    - {op_name}: {s * 1e3:.0f}ms")
        return "\n".join(lines)

    __str__ = summary

    def __repr__(self):
        return self.summary()


EMPTY_STATS = DatasetStats({}, 0.0, executed=False)


class StatsBuilder:
    """Mutable driver-side accumulator: one per execution.

    Meta refs resolve lazily in build() — the executor never blocks the
    pipeline on stats fetches; `Dataset.stats()` pays the (tiny-object)
    gets when asked.
    """

    def __init__(self, stage_names: List[str]):
        self._order = list(stage_names)
        self._tasks: Dict[str, int] = {n: 0 for n in self._order}
        self._throttled: Dict[str, Dict[str, int]] = {n: {} for n in self._order}
        self._meta_refs: Dict[str, List[Any]] = {n: [] for n in self._order}
        self._driver_counts: Dict[str, Dict[str, int]] = {}
        self.t_start = time.perf_counter()
        self.t_end: Optional[float] = None
        self._finalized = False
        self._launches_complete = False
        self._built: Optional[DatasetStats] = None
        self._published_driver = False  # tasks/throttles (at finalize)
        self._published_meta = False    # rows/bytes/time (at settled build)

    def _ensure(self, stage: str):
        if stage not in self._tasks:
            self._order.append(stage)
            self._tasks[stage] = 0
            self._throttled[stage] = {}
            self._meta_refs[stage] = []

    def task_launched(self, stage: str, n: int = 1):
        self._ensure(stage)
        self._tasks[stage] += n

    def throttled(self, stage: str, policy: str):
        self._ensure(stage)
        t = self._throttled[stage]
        t[policy] = t.get(policy, 0) + 1

    def add_meta(self, stage: str, meta_ref):
        self._ensure(stage)
        self._meta_refs[stage].append(meta_ref)

    def add_driver_counts(self, stage: str, **counts: int):
        self._ensure(stage)
        d = self._driver_counts.setdefault(stage, {})
        for k, v in counts.items():
            d[k] = d.get(k, 0) + v

    def mark_launches_complete(self):
        """Eager path: every task has been LAUNCHED (though maybe not
        finished). Once their metas all resolve, the snapshot is final
        and may cache."""
        self._launches_complete = True

    def finalize(self):
        """Mark the execution complete (called by the executor when the
        pipeline drains or is closed). Only finalized builders cache
        their built snapshot. Driver-side counters (launches, throttles)
        bridge into the metrics pipeline HERE — no ref waits on the
        drain path; the task-side sums follow when stats() settles."""
        if self.t_end is None:
            self.t_end = time.perf_counter()
        self._finalized = True
        self._launches_complete = True
        if not self._published_driver:
            self._published_driver = True
            try:
                m = _data_metrics()
                for name in self._order:
                    tags = {"operator": name}
                    if self._tasks.get(name):
                        m["tasks"].inc(self._tasks[name], tags=tags)
                    for policy, n in self._throttled.get(name, {}).items():
                        m["throttles"].inc(n, tags={**tags, "policy": policy})
            except Exception:
                pass

    def build(self, *, timeout: float = 120.0) -> DatasetStats:
        """Resolve task-side metas into a snapshot. A stats() call
        MID-execution sees the progress so far and must not freeze it:
        only a finalized execution caches (and skips refetching on
        repeated calls)."""
        if self._built is not None:
            return self._built
        import ray_tpu

        t_end = self.t_end if self.t_end is not None else time.perf_counter()
        all_resolved = True
        operators: Dict[str, Dict[str, Any]] = {}
        for name in self._order:
            m: Dict[str, Any] = {
                "tasks": self._tasks[name],
                "throttled": dict(self._throttled[name]),
            }
            refs = self._meta_refs[name]
            if refs:
                try:
                    ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs), timeout=timeout)
                except Exception:
                    ready, not_ready = [], refs
                if not_ready:
                    all_resolved = False
                metas = []
                for ref in ready:
                    # per-ref get: a failed task's meta raises its error;
                    # the healthy tasks' metas must still be counted
                    try:
                        meta = ray_tpu.get(ref)
                    except Exception:
                        continue
                    if isinstance(meta, dict):
                        metas.append(meta)
                if metas:
                    m["rows_in"] = sum(x["rows_in"] for x in metas)
                    m["rows_out"] = sum(x["rows_out"] for x in metas)
                    m["bytes_in"] = sum(x["bytes_in"] for x in metas)
                    m["bytes_out"] = sum(x["bytes_out"] for x in metas)
                    m["task_s"] = sum(x["task_s"] for x in metas)
                    per: Dict[str, float] = {}
                    for x in metas:
                        for k, v in (x.get("per_op_s") or {}).items():
                            per[k] = per.get(k, 0.0) + v
                    if per:
                        m["per_op_s"] = per
                    for key in _EXCHANGE_KEYS:
                        total = sum(x.get(key, 0) for x in metas)
                        if total:
                            m[key] = total
            for k, v in self._driver_counts.get(name, {}).items():
                m[k] = m.get(k, 0) + v
            operators[name] = m
        built = DatasetStats(operators, t_end - self.t_start)
        # cache a finalized execution's snapshot; an eager execution
        # (all launches issued, never stream-finalized) caches once
        # every task meta resolved — repeated stats() calls must not
        # refetch or drift the wall time. A mid-stream snapshot (more
        # launches may come) is never cached.
        if self._finalized or (self._launches_complete and all_resolved):
            self._built = built
            self._publish_metrics(built)
        return built

    def _publish_metrics(self, built: DatasetStats) -> None:
        """Once per execution, when the snapshot settles: the task-side
        sums (rows/bytes/time) join the metrics pipeline, and the whole
        stats dict ships as the "data" telemetry snapshot so the
        dashboard's /api/data serves the latest execution. Mid-stream
        snapshots never publish — they would double-count when the
        final one lands. Launch/throttle counters already published at
        finalize()."""
        if self._published_meta:
            return
        self._published_meta = True
        try:
            from ray_tpu import observability

            observability.publish_snapshot("data", {"dataset": built.to_dict()})
        except Exception:
            pass
        try:
            m = _data_metrics()
            publish_driver = not self._published_driver
            self._published_driver = True
            for name, op in built.operators.items():
                tags = {"operator": name}
                if publish_driver:
                    # eager path: no finalize() — launches publish here
                    if op.get("tasks"):
                        m["tasks"].inc(op["tasks"], tags=tags)
                    for policy, n in op.get("throttled", {}).items():
                        m["throttles"].inc(n, tags={**tags, "policy": policy})
                if op.get("rows_out"):
                    m["rows_out"].inc(op["rows_out"], tags=tags)
                if op.get("bytes_out"):
                    m["bytes_out"].inc(op["bytes_out"], tags=tags)
                if op.get("task_s"):
                    m["task_time"].inc(op["task_s"], tags=tags)
                for key in _EXCHANGE_KEYS:
                    if op.get(key):
                        m[key].inc(op[key], tags=tags)
        except Exception:
            pass
