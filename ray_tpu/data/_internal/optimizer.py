"""Logical-plan optimizer + physical planning.

Equivalent of the reference's logical optimizer rules + planner
(reference: python/ray/data/_internal/logical/rules/operator_fusion.py
and .../limit_pushdown.py — there rewrite rules run over the logical DAG
before physical planning). The chain here is linear, so rules are bubble
passes over a list:

1. **Limit pushdown** — a `Limit` moves left past any
   `limit_pushdown_safe` operator (per-row map, projections — NOT
   add_column, whose fn sees the whole batch and would observe fewer
   rows after a reorder), and adjacent limits merge to their min. A
   limit that reaches the front of the chain stops SOURCE READS: the
   executor pulls no more lazy blocks once the budget is met, so
   `read_parquet(...).limit(k)` launches only the prefix of read tasks.
   (Projections are deliberately NOT hopped rightward past limits —
   the two rules would ping-pong; limit moving left subsumes the win.)
2. **Projection merges** — adjacent select/select (when provably
   narrowing) and drop/drop runs collapse. (True projection pushdown
   INTO reads needs column-aware readers; the read tasks here produce
   whole files, so the projection stops at the first task stage.)
3. **Operator fusion** — contiguous runs of fusable narrow operators
   become ONE `TaskStage`: one task per block for the whole run instead
   of one task per operator per block (reference: operator_fusion.py
   fusing Map->Map chains into a single MapOperator).

`build_plan` lowers the optimized chain to physical stages the executor
walks: `TaskStage` (fused task per block), `ActorStage` (stateful
actor-pool map) and `LimitStage` (driver-enforced global row budget).
"""
from __future__ import annotations

from typing import List, Optional

from ray_tpu.data._internal.logical_ops import (
    DropColumns,
    Exchange,
    Limit,
    LogicalOp,
    MapBatches,
    SelectColumns,
    as_op,
)


class Stage:
    name: str = "?"

    def __repr__(self):
        return self.name


class TaskStage(Stage):
    """A fused run of narrow ops: one task per block."""

    def __init__(self, ops: List[LogicalOp]):
        self.ops = ops
        self.name = "->".join(op.name for op in ops)


class ActorStage(Stage):
    """A stateful actor-pool map_batches stage."""

    def __init__(self, op: MapBatches):
        self.op = op
        self.name = op.name


class LimitStage(Stage):
    """Global first-n-rows, enforced by the executor (stops upstream
    pulls, slices the boundary block in a task)."""

    def __init__(self, n: int):
        self.n = n
        self.name = f"Limit[{n}]"


class ExchangeStage(Stage):
    """Streaming all-to-all exchange (data/_internal/exchange.py). Any
    run of fusable narrow ops immediately upstream folds into the
    mappers (`mapper_ops`) — one task per block applies the whole chain
    AND partitions, exactly like the seed shuffle's fused map stage."""

    def __init__(self, op: Exchange, mapper_ops: Optional[List[LogicalOp]] = None):
        self.op = op
        self.mapper_ops = mapper_ops or []
        self.name = op.name
        # the stage owns TWO launch windows: mapper tasks and reducer
        # finalizes — separate names so caps/stats/metas don't alias
        self.map_name = f"ExchangeMap[{op.mode}]"


def optimize(ops: List[LogicalOp], *, limit_pushdown: bool = True) -> List[LogicalOp]:
    """Rewrite the logical chain: limit pushdown + merges. Pure —
    returns a new list, never mutates operators."""
    out = list(ops)
    if not limit_pushdown:
        return out
    changed = True
    while changed:
        changed = False
        i = 1
        while i < len(out):
            cur, prev = out[i], out[i - 1]
            if isinstance(cur, Limit) and isinstance(prev, Limit):
                out[i - 1 : i + 1] = [Limit(min(cur.n, prev.n))]
                changed = True
                continue
            if isinstance(cur, Limit) and prev.limit_pushdown_safe:
                out[i - 1], out[i] = cur, prev
                changed = True
                i += 1
                continue
            if (
                isinstance(cur, SelectColumns)
                and isinstance(prev, SelectColumns)
                and set(cur.cols) <= set(prev.cols)
            ):
                # select(b) after select(a), b ⊆ a — the outer projection
                # subsumes the inner one (b ⊄ a would have raised anyway,
                # but only the provably-narrowing case is rewritten)
                out[i - 1 : i + 1] = [cur]
                changed = True
                continue
            if isinstance(cur, DropColumns) and isinstance(prev, DropColumns):
                out[i - 1 : i + 1] = [DropColumns(prev.cols + [c for c in cur.cols if c not in prev.cols])]
                changed = True
                continue
            i += 1
    return out


def build_plan(
    ops: Optional[List],
    *,
    fusion: bool = True,
    limit_pushdown: bool = True,
) -> List[Stage]:
    """Lower an ops chain (typed or legacy tuples) to physical stages."""
    typed = [as_op(op) for op in ops or []]
    typed = optimize(typed, limit_pushdown=limit_pushdown)
    stages: List[Stage] = []
    run: List[LogicalOp] = []

    def flush():
        nonlocal run
        if run:
            if fusion:
                stages.append(TaskStage(run))
            else:
                stages.extend(TaskStage([op]) for op in run)
            run = []

    for op in typed:
        if isinstance(op, MapBatches) and op.is_actor_pool:
            flush()
            stages.append(ActorStage(op))
        elif isinstance(op, Limit):
            flush()
            stages.append(LimitStage(op.n))
        elif isinstance(op, Exchange):
            # steal the pending fused run into the exchange's mappers:
            # apply-chain + partition in ONE task per block instead of a
            # separate task stage feeding the exchange
            mapper_ops, run = run, []
            if not fusion:
                # fusion off (debug): keep per-op stages, bare mappers
                for o in mapper_ops:
                    stages.append(TaskStage([o]))
                mapper_ops = []
            stages.append(ExchangeStage(op, mapper_ops))
        else:
            run.append(op)
    flush()
    # stage names key the shared in-flight counters, caps and stats —
    # two same-shaped stages (e.g. twin lambda map_batches) MUST NOT
    # alias each other's window or the pipeline deadlocks
    seen: dict = {}
    for s in stages:
        n = seen.get(s.name, 0)
        seen[s.name] = n + 1
        if n:
            s.name = f"{s.name}#{n + 1}"
            if isinstance(s, ExchangeStage):
                s.map_name = f"{s.map_name}#{n + 1}"
    return stages


def has_actor_stage(ops: Optional[List]) -> bool:
    return any(
        isinstance(o, MapBatches) and o.is_actor_pool
        for o in (as_op(op) for op in ops or [])
    )


def has_limit(ops: Optional[List]) -> bool:
    return any(isinstance(as_op(op), Limit) for op in ops or [])


def has_barrier(ops: Optional[List]) -> bool:
    """True when the chain contains an op that cannot be applied
    independently per block (Limit's global budget, Exchange's
    all-to-all) — such chains must execute through the plan before a
    per-block consumer (shuffle maps, preprocessor fits) may run."""
    return any(isinstance(as_op(op), (Limit, Exchange)) for op in ops or [])
