"""DataContext — process-wide execution knobs for ray_tpu.data.

Equivalent of the reference's DataContext (reference:
python/ray/data/context.py — a singleton of execution options the
planner and executor consult). Mutate the singleton to tune a pipeline:

    ctx = ray_tpu.data.DataContext.get_current()
    ctx.arena_usage_fraction = 0.5   # throttle launches above 50% arena
    ctx.operator_fusion = False      # debug: one task per operator
"""
from __future__ import annotations

from typing import List, Optional


class DataContext:
    """Singleton of data-execution options."""

    _current: Optional["DataContext"] = None

    def __init__(self):
        # -- plan optimization ------------------------------------------
        self.operator_fusion: bool = True     # fuse narrow-op runs into one task/block
        self.limit_pushdown: bool = True      # move Limit toward the sources

        # -- backpressure ----------------------------------------------
        # global streaming in-flight budget, split across stage windows
        # (iter_batches derives its own from prefetch_blocks)
        self.max_in_flight_blocks: int = 8
        # eager materialization window when a plan needs streaming stages
        self.eager_max_in_flight: int = 16
        # arena-usage policy: throttle launches above this fraction of
        # shm-arena capacity (None disables the policy)
        self.arena_usage_fraction: Optional[float] = 0.75
        # absolute byte budget overriding the fraction (tests / tight SLAs)
        self.arena_usage_budget_bytes: Optional[int] = None
        # driver poll interval while a policy refuses launches
        self.backpressure_poll_interval_s: float = 0.002
        # extra policies appended to the defaults (BackpressurePolicy)
        self.extra_backpressure_policies: List = []

        # -- actor-pool stages -----------------------------------------
        self.actor_max_tasks_in_flight: int = 2

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = DataContext()
        return cls._current
