"""DataContext — process-wide execution knobs for ray_tpu.data.

Equivalent of the reference's DataContext (reference:
python/ray/data/context.py — a singleton of execution options the
planner and executor consult). Mutate the singleton to tune a pipeline:

    ctx = ray_tpu.data.DataContext.get_current()
    ctx.arena_usage_fraction = 0.5   # throttle launches above 50% arena
    ctx.operator_fusion = False      # debug: one task per operator
"""
from __future__ import annotations

from typing import List, Optional


class DataContext:
    """Singleton of data-execution options."""

    _current: Optional["DataContext"] = None

    def __init__(self):
        # -- plan optimization ------------------------------------------
        self.operator_fusion: bool = True     # fuse narrow-op runs into one task/block
        self.limit_pushdown: bool = True      # move Limit toward the sources

        # -- backpressure ----------------------------------------------
        # global streaming in-flight budget, split across stage windows
        # (iter_batches derives its own from prefetch_blocks)
        self.max_in_flight_blocks: int = 8
        # eager materialization window when a plan needs streaming stages
        self.eager_max_in_flight: int = 16
        # arena-usage policy: throttle launches above this fraction of
        # shm-arena capacity (None disables the policy)
        self.arena_usage_fraction: Optional[float] = 0.75
        # absolute byte budget overriding the fraction (tests / tight SLAs)
        self.arena_usage_budget_bytes: Optional[int] = None
        # driver poll interval while a policy refuses launches
        self.backpressure_poll_interval_s: float = 0.002
        # extra policies appended to the defaults (BackpressurePolicy)
        self.extra_backpressure_policies: List = []

        # -- actor-pool stages -----------------------------------------
        self.actor_max_tasks_in_flight: int = 2

        # -- streaming exchange (shuffle/sort/repartition/groupby) ------
        # False restores the seed-era 2-stage shuffle (data/_shuffle.py):
        # N×M part refs through the object store, hierarchical fan-in
        self.use_streaming_exchange: bool = True
        # chunks ride shm rings between colocated mappers/reducers;
        # False forces the put/get (object-plane) path everywhere
        self.exchange_use_rings: bool = True
        # reducer actors per exchange (pooled across exchanges); each
        # owns M/R partitions and one ring
        self.exchange_num_reducers: int = 2
        # byte ring per (reducer, exchange): ring-full blocks mappers —
        # this IS the transport-level backpressure bound
        self.exchange_ring_capacity: int = 16 * 1024 * 1024
        # partition parts are pushed in chunks of at most this many bytes
        # (bigger chunks amortize per-record costs; the ring must hold a
        # few records so writers keep streaming while the reducer drains)
        self.exchange_chunk_bytes: int = 2 * 1024 * 1024

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = DataContext()
        return cls._current
