"""@remote functions.

Equivalent of the reference's RemoteFunction
(reference: python/ray/remote_function.py:138 _remote_proxy/_remote and
the @ray.remote decorator at python/ray/_private/worker.py:3242).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.object_ref import ObjectRef


def _normalize_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    res: Dict[str, float] = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    else:
        res.setdefault("CPU", 1.0)
    if opts.get("num_tpus") is not None:
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus") is not None:  # parity shim: GPU as a plain resource
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("memory") is not None:
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v}


def _scheduling_fields(opts: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if opts.get("runtime_env"):
        # per-task env (env_vars overlay; reference: per-task runtime_env)
        out["runtime_env"] = opts["runtime_env"]
    strategy = opts.get("scheduling_strategy")
    if strategy is not None:
        if isinstance(strategy, str):
            out["scheduling_strategy"] = strategy
        else:
            # strategy objects from ray_tpu.util.scheduling_strategies
            out.update(strategy.to_spec_fields())
    pg = opts.get("placement_group")
    if pg is not None:
        out["placement_group_id"] = pg.id if hasattr(pg, "id") else pg
        out["bundle_index"] = opts.get("placement_group_bundle_index", -1)
    return out


class RemoteFunction:
    def __init__(self, fn, **default_opts):
        self._fn = fn
        self._opts = default_opts
        self._fn_id: Optional[str] = None
        self._exported_by: Optional[int] = None
        self._resources: Optional[Dict[str, float]] = None
        self._scheduling: Optional[Dict[str, Any]] = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._fn.__name__}' cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )

    def options(self, **opts) -> "RemoteFunction":
        merged = {**self._opts, **opts}
        rf = RemoteFunction(self._fn, **merged)
        rf._fn_id = self._fn_id
        rf._exported_by = self._exported_by
        return rf

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import get_global_core

        core = get_global_core()
        if self._fn_id is None or self._exported_by != id(core):
            self._fn_id = core.export_function(self._fn)
            self._exported_by = id(core)
        if self._resources is None:
            # options are immutable per RemoteFunction instance: normalize
            # once instead of rebuilding dicts per call
            self._resources = _normalize_resources(self._opts)
            self._scheduling = _scheduling_fields(self._opts)
        num_returns = self._opts.get("num_returns", 1)
        refs = core.submit_task(
            fn_id=self._fn_id,
            args=args,
            kwargs=kwargs,
            name=self._opts.get("name", self._fn.__name__),
            num_returns=num_returns,
            resources=self._resources,
            max_retries=self._opts.get("max_retries"),
            scheduling=self._scheduling,
        )
        return refs[0] if num_returns == 1 else refs

    @property
    def bind(self):
        from ray_tpu.dag import FunctionNode

        def _bind(*args, **kwargs):
            return FunctionNode(self, args, kwargs)

        return _bind
