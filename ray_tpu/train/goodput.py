"""Goodput accounting for preemption-tolerant training.

Goodput = the fraction of wall time that advanced the model. PR-4's
step telemetry already measures inter-step gaps for a HEALTHY loop;
this meter prices the UNHEALTHY part — what a preemption actually
cost, split into the phases the recovery pipeline goes through:

  detect     — dead/hung slice noticed (probe timeout, failed dispatch)
  regang     — membership change: generation bump, survivor re-plan
  restore    — state broadcast (survivor D2H → re-admitted slice H2D)
  recompile  — first-step warmup on the re-admitted slice
  checkpoint_stall — synchronous part of checkpoint saves (D2H snapshot)

The breakdown is what makes the bill actionable: a fat `restore` says
ship Gemini-style peer state transfer, a fat `recompile` says persist
the compilation cache, a fat `detect` says tighten probe timeouts.

`summary()` feeds `/api/training` (via observability.publish_snapshot)
and bench.py's elastic section; the ROADMAP bench gate is
goodput ≥ 95% under injected preemptions.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterator, Optional

RECOVERY_PHASES = ("detect", "regang", "restore", "recompile", "checkpoint_stall")


class GoodputMeter:
    """Wall-clock ledger: everything not explicitly booked as lost is
    productive. Thread-safe — slice probes and the checkpoint writer
    report from their own threads."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None
        self._lost: Dict[str, float] = {p: 0.0 for p in RECOVERY_PHASES}
        self._events: int = 0
        self._steps: int = 0
        self._degraded_steps: int = 0

    # ----------------------------------------------------------- running
    def start(self) -> "GoodputMeter":
        with self._lock:
            if self._t_start is None:
                self._t_start = self._clock()
        return self

    def stop(self) -> None:
        with self._lock:
            if self._t_start is not None and self._t_stop is None:
                self._t_stop = self._clock()

    def step_done(self, *, degraded: bool = False) -> None:
        with self._lock:
            self._steps += 1
            if degraded:
                self._degraded_steps += 1

    # -------------------------------------------------------------- lost
    def add_lost(self, phase: str, seconds: float) -> None:
        with self._lock:
            self._lost[phase] = self._lost.get(phase, 0.0) + max(0.0, seconds)

    @contextlib.contextmanager
    def lost(self, phase: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self.add_lost(phase, self._clock() - t0)

    def recovery_event(self) -> None:
        """One preemption survived (a degrade or a re-admit cycle)."""
        with self._lock:
            self._events += 1

    # ----------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            if self._t_start is None:
                return {"goodput_pct": None, "wall_s": 0.0}
            end = self._t_stop if self._t_stop is not None else self._clock()
            wall = max(end - self._t_start, 1e-9)
            lost = dict(self._lost)
            lost_total = sum(lost.values())
            return {
                "goodput_pct": round(100.0 * max(wall - lost_total, 0.0) / wall, 2),
                "wall_s": round(wall, 4),
                "lost_s": round(lost_total, 4),
                "recovery_breakdown_s": {k: round(v, 4) for k, v in lost.items()},
                "recovery_events": self._events,
                "steps": self._steps,
                "degraded_steps": self._degraded_steps,
            }

    def publish(self) -> Dict[str, Any]:
        """Push the summary into the "training" telemetry snapshot so
        the dashboard's /api/training serves it next to MFU/step-time.
        Best-effort: accounting must never fail training."""
        s = self.summary()
        try:
            from ray_tpu import observability

            observability.publish_snapshot("training", {"elastic": s})
        except Exception:
            pass
        return s
