"""GBDTTrainer — distributed gradient-boosted decision trees.

Equivalent of the reference's GBDT trainers
(reference: python/ray/train/gbdt_trainer.py + xgboost/xgboost_trainer.py,
lightgbm/lightgbm_trainer.py — thin wrappers around distributed
xgboost/lightgbm). Those libraries aren't in this image, so the
capability is implemented natively: histogram-based boosting in the
xgboost "approx" shape — quantile feature binning, per-shard
gradient/hessian histograms computed as tasks over Dataset blocks,
driver-side split search and level-wise tree growth. The distributed
pattern matches the reference's: data stays sharded in the object
store; only fixed-size histograms (bins x features x 2 floats) travel
per boosting round.

Supports squared-error regression and binary logistic classification.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu


@ray_tpu.remote
def _bin_shard(blk, feature_columns, label_column, edges):
    """Bin one block's features; returns (binned uint8 [N,F], labels)."""
    import numpy as np

    from ray_tpu.data import block as B

    rows = B.block_to_batch(blk, "numpy")
    X = np.stack([np.asarray(rows[c], np.float64) for c in feature_columns], 1)
    y = np.asarray(rows[label_column], np.float64)
    binned = np.empty(X.shape, np.uint8)
    for f in range(X.shape[1]):
        binned[:, f] = np.searchsorted(edges[f], X[:, f], side="left")
    return binned, y


@ray_tpu.remote
def _histogram_shard(binned_labels, preds, n_bins, node_ids, n_nodes, objective):
    """Per-shard grad/hess histograms for every open node:
    [n_nodes, F, n_bins, 2]."""
    import numpy as np

    binned, y = binned_labels
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + np.exp(-preds))
        grad = p - y
        hess = np.maximum(p * (1.0 - p), 1e-9)
    else:
        grad = preds - y
        hess = np.ones_like(y)
    N, F = binned.shape
    out = np.zeros((n_nodes, F, n_bins, 2), np.float64)
    for node in range(n_nodes):
        mask = node_ids == node
        if not mask.any():
            continue
        b = binned[mask]
        g = grad[mask]
        h = hess[mask]
        for f in range(F):
            out[node, f, :, 0] = np.bincount(b[:, f], weights=g, minlength=n_bins)
            out[node, f, :, 1] = np.bincount(b[:, f], weights=h, minlength=n_bins)
    return out


@ray_tpu.remote
def _apply_tree_shard(binned_labels, node_ids, splits):
    """Route each shard row one level down: splits = {node: (f, bin)};
    children ids are 2*node+1 / 2*node+2 in a level-order numbering."""
    import numpy as np

    binned, _ = binned_labels
    # rows of nodes that became leaves this level KEEP their node id so
    # the leaf-value update still reaches them
    new_ids = node_ids.copy()
    for node, (f, thr_bin) in splits.items():
        mask = node_ids == node
        go_left = binned[mask, f] <= thr_bin
        ids = np.where(go_left, 2 * node + 1, 2 * node + 2)
        new_ids[mask] = ids
    return new_ids


@ray_tpu.remote
def _update_preds_shard(preds, node_ids, leaf_values, lr):
    import numpy as np

    leaf = np.asarray([leaf_values.get(int(n), 0.0) for n in node_ids])
    return preds + lr * leaf


class _Tree:
    """One regression tree: parallel-array nodes in level-order
    numbering (node k's children are 2k+1 / 2k+2)."""

    def __init__(self, max_depth: int):
        size = 2 ** (max_depth + 1) - 1
        self.feature = np.full(size, -1, np.int32)
        self.threshold = np.zeros(size, np.float64)  # raw-value threshold
        self.value = np.zeros(size, np.float64)
        self.is_leaf = np.zeros(size, bool)

    def predict(self, X: np.ndarray) -> np.ndarray:
        out = np.empty(len(X))
        for i, x in enumerate(X):
            node = 0
            while not self.is_leaf[node] and self.feature[node] >= 0:
                node = 2 * node + 1 if x[self.feature[node]] <= self.threshold[node] else 2 * node + 2
            out[i] = self.value[node]
        return out


class GBDTModel:
    """A fitted booster: bias + lr-scaled trees."""

    def __init__(self, trees: List[_Tree], bias: float, lr: float,
                 feature_columns: List[str], objective: str):
        self.trees = trees
        self.bias = bias
        self.lr = lr
        self.feature_columns = feature_columns
        self.objective = objective

    def predict(self, X) -> np.ndarray:
        if isinstance(X, dict):
            X = np.stack([np.asarray(X[c], np.float64) for c in self.feature_columns], 1)
        X = np.asarray(X, np.float64)
        raw = np.full(len(X), self.bias)
        for t in self.trees:
            raw = raw + self.lr * t.predict(X)
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-raw))
        return raw


class GBDTTrainer:
    """Distributed histogram GBDT (reference: train/gbdt_trainer.py API
    shape — datasets + label_column + params; `fit()` returns a result
    with the fitted model)."""

    def __init__(self, *, datasets: Dict[str, Any], label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 feature_columns: Optional[List[str]] = None,
                 num_boost_round: int = 20):
        self.train_ds = datasets["train"]
        self.label_column = label_column
        p = dict(params or {})
        self.objective = p.get("objective", "reg:squarederror")
        self.max_depth = int(p.get("max_depth", 3))
        self.lr = float(p.get("eta", p.get("learning_rate", 0.3)))
        self.reg_lambda = float(p.get("lambda", 1.0))
        self.min_child_weight = float(p.get("min_child_weight", 1.0))
        self.n_bins = int(p.get("max_bin", 32))
        self.num_boost_round = num_boost_round
        self.feature_columns = feature_columns

    def fit(self) -> "GBDTResult":
        refs = self.train_ds._execute_refs()
        # column discovery + quantile bin edges from the first block
        from ray_tpu.data import block as B

        first = B.block_to_batch(ray_tpu.get(refs[0]), "numpy")
        feats = self.feature_columns or [c for c in first.keys() if c != self.label_column]
        sample = np.stack([np.asarray(first[c], np.float64) for c in feats], 1)
        qs = np.linspace(0, 1, self.n_bins)[1:]
        edges = [np.unique(np.quantile(sample[:, f], qs)) for f in range(len(feats))]

        binned_refs = [_bin_shard.remote(r, feats, self.label_column, edges) for r in refs]
        # ONE materialization for sizes + label sums; afterwards only
        # fixed-size histograms travel per boosting round
        shards = ray_tpu.get(binned_refs)
        shard_sizes = [len(b[1]) for b in shards]
        total = sum(shard_sizes)
        mean_y = sum(float(np.sum(b[1])) for b in shards) / total
        del shards
        if self.objective == "binary:logistic":
            mean_y = min(max(mean_y, 1e-6), 1 - 1e-6)
            bias = math.log(mean_y / (1 - mean_y))
        else:
            bias = mean_y

        pred_refs = [ray_tpu.put(np.full(n, bias)) for n in shard_sizes]
        trees: List[_Tree] = []
        for _ in range(self.num_boost_round):
            tree, pred_refs = self._boost_one(binned_refs, pred_refs, feats, edges, shard_sizes)
            trees.append(tree)
        self.model = GBDTModel(trees, bias, self.lr, feats, self.objective)
        return GBDTResult(self.model)

    def _boost_one(self, binned_refs, pred_refs, feats, edges, shard_sizes) -> Tuple[_Tree, list]:
        F = len(feats)
        n_bins = self.n_bins
        tree = _Tree(self.max_depth)
        # node ids per shard, level-order numbering
        id_refs = [ray_tpu.put(np.zeros(n, np.int64)) for n in shard_sizes]
        open_nodes = [0]
        for depth in range(self.max_depth):
            hist_refs = [
                _histogram_shard.remote(b, p, n_bins, i, 2 ** (depth + 1) - 1, self.objective)
                for b, p, i in zip(binned_refs, pred_refs, id_refs)
            ]
            hist = sum(ray_tpu.get(hist_refs))  # [nodes, F, bins, 2]
            splits: Dict[int, Tuple[int, int]] = {}
            next_open = []
            for node in open_nodes:
                G = hist[node, :, :, 0]
                H = hist[node, :, :, 1]
                g_tot, h_tot = G[0].sum(), H[0].sum()
                base = g_tot * g_tot / (h_tot + self.reg_lambda)
                best_gain, best = 0.0, None
                gl = np.cumsum(G, 1)
                hl = np.cumsum(H, 1)
                gr = g_tot - gl
                hr = h_tot - hl
                valid = (hl >= self.min_child_weight) & (hr >= self.min_child_weight)
                gain = gl**2 / (hl + self.reg_lambda) + gr**2 / (hr + self.reg_lambda) - base
                gain = np.where(valid, gain, -np.inf)
                f, b = np.unravel_index(np.argmax(gain), gain.shape)
                if gain[f, b] > 1e-12 and np.isfinite(gain[f, b]):
                    splits[node] = (int(f), int(b))
                    tree.feature[node] = int(f)
                    thr_edges = edges[f]
                    tree.threshold[node] = thr_edges[min(int(b), len(thr_edges) - 1)]
                    next_open += [2 * node + 1, 2 * node + 2]
                else:
                    tree.is_leaf[node] = True
                    tree.value[node] = -g_tot / (h_tot + self.reg_lambda)
            if not splits:
                break
            id_refs = [
                _apply_tree_shard.remote(b, i, splits)
                for b, i in zip(binned_refs, id_refs)
            ]
            open_nodes = next_open
        # leaves at the frontier
        if open_nodes:
            hist_refs = [
                _histogram_shard.remote(b, p, n_bins, i, 2 ** (self.max_depth + 1) - 1, self.objective)
                for b, p, i in zip(binned_refs, pred_refs, id_refs)
            ]
            hist = sum(ray_tpu.get(hist_refs))
            for node in open_nodes:
                g_tot = hist[node, 0, :, 0].sum()
                h_tot = hist[node, 0, :, 1].sum()
                tree.is_leaf[node] = True
                tree.value[node] = -g_tot / (h_tot + self.reg_lambda)
        leaf_values = {
            int(i): float(v) for i, v in enumerate(tree.value) if tree.is_leaf[i]
        }
        pred_refs = [
            _update_preds_shard.remote(p, i, leaf_values, self.lr)
            for p, i in zip(pred_refs, id_refs)
        ]
        return tree, pred_refs


class GBDTResult:
    def __init__(self, model: GBDTModel):
        self.model = model
        self.checkpoint = None
