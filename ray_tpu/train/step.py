"""GSPMD training-step builder.

Produces the jitted SPMD train step that replaces the reference's
DDP/NCCL inner loop (reference: train/torch/train_loop_utils.py
prepare_model + loss.backward + allreduce): params/opt-state sharded per
the strategy's logical-axis rules, batch sharded on (dp, fsdp), gradient
reduction emitted by XLA as ICI collectives — no process groups.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.sharding import LogicalAxisRules


def make_train_state(params, tx):
    return {"params": params, "opt": tx.init(params), "step": jnp.zeros((), jnp.int32)}


def build_sharded_train_step(
    cfg,
    mesh,
    strategy: str = "fsdp",
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    model=None,
) -> Tuple[Callable, Callable, Any, "LogicalAxisRules"]:
    """Returns (init_fn, step_fn, tx, rules).

    init_fn(rng, batch_shape) -> sharded train state on the mesh.
    step_fn(state, batch) -> (state, metrics) — fully jitted SPMD.
    """
    from ray_tpu.models import llama as L

    model = model or L
    rules = LogicalAxisRules.for_strategy(strategy)
    axes = model.logical_axes(cfg)

    tx = optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )

    param_shardings = jax.tree.map(
        lambda ax: rules.named_sharding(mesh, ax),
        axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(i, (str, type(None))) for i in x),
    )
    batch_sharding = rules.named_sharding(mesh, ("batch", None))
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())

    def loss(params, batch):
        return model.loss_fn(params, batch, cfg, mesh, rules)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, batch):
        l, grads = jax.value_and_grad(loss)(state["params"], batch)
        updates, opt = tx.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        return (
            {"params": params, "opt": opt, "step": state["step"] + 1},
            {"loss": l, "grad_norm": gnorm, "step": state["step"] + 1},
        )

    def init_fn(rng):
        params = model.init_params(rng, cfg)
        params = jax.tree.map(
            lambda p, sh: jax.device_put(p, sh), params, param_shardings
        )
        # opt state init under jit so mu/nu inherit param shardings
        opt = jax.jit(tx.init)(params)
        return {"params": params, "opt": opt, "step": jnp.zeros((), jnp.int32)}

    def shard_batch(batch):
        return jax.tree.map(lambda x: jax.device_put(x, batch_sharding), batch)

    return init_fn, step_fn, shard_batch, rules
