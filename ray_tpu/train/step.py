"""GSPMD training-step builder.

Produces the jitted SPMD train step that replaces the reference's
DDP/NCCL inner loop (reference: train/torch/train_loop_utils.py
prepare_model + loss.backward + allreduce): params/opt-state sharded per
the strategy's logical-axis rules, batch sharded on (dp, fsdp), gradient
reduction emitted by XLA as ICI collectives — no process groups.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.sharding import LogicalAxisRules


def make_train_state(params, tx):
    return {"params": params, "opt": tx.init(params), "step": jnp.zeros((), jnp.int32)}


def build_sharded_train_step(
    cfg,
    mesh,
    strategy: str = "fsdp",
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    model=None,
    telemetry: bool = True,
    telemetry_name: str = "train_step",
) -> Tuple[Callable, Callable, Any, "LogicalAxisRules"]:
    """Returns (init_fn, step_fn, tx, rules).

    init_fn(rng, batch_shape) -> sharded train state on the mesh.
    step_fn(state, batch) -> (state, metrics) — fully jitted SPMD.

    `telemetry=True` (default) wraps step_fn with
    observability.instrument_step: per-step wall time, goodput, compile
    events and a live MFU estimate (FLOPs from the model's analytic
    `flops_per_token` at the batch's token shape) flow to the metrics
    pipeline and the unified trace at zero change to the compiled HLO.
    """
    from ray_tpu.models import llama as L

    model = model or L
    rules = LogicalAxisRules.for_strategy(strategy)
    axes = model.logical_axes(cfg)

    tx = optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )

    param_shardings = jax.tree.map(
        lambda ax: rules.named_sharding(mesh, ax),
        axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(i, (str, type(None))) for i in x),
    )
    batch_sharding = rules.named_sharding(mesh, ("batch", None))
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())

    def loss(params, batch):
        return model.loss_fn(params, batch, cfg, mesh, rules)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, batch):
        l, grads = jax.value_and_grad(loss)(state["params"], batch)
        updates, opt = tx.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        return (
            {"params": params, "opt": opt, "step": state["step"] + 1},
            {"loss": l, "grad_norm": gnorm, "step": state["step"] + 1},
        )

    if telemetry:
        from ray_tpu.observability import instrument_step

        flops_fn = getattr(model, "flops_per_token", None)
        _flops_cache: Dict[Tuple[int, ...], float] = {}

        def _step_flops(args, kwargs):
            # batch tokens are [B, T+1] (inputs+shifted targets); the
            # analytic FLOPs are per TRAINED token. Cached per shape —
            # the math is cheap but the hot path should not repeat it.
            if flops_fn is None:
                return None
            try:
                tokens = args[1]["tokens"]
                key = tuple(tokens.shape)
                if key not in _flops_cache:
                    b, t1 = tokens.shape
                    _flops_cache[key] = flops_fn(cfg, t1 - 1) * b * (t1 - 1)
                return _flops_cache[key]
            except Exception:
                return None

        step_fn = instrument_step(
            step_fn, name=telemetry_name, flops_per_call=_step_flops,
            kind="training",
        )

    def init_fn(rng):
        params = model.init_params(rng, cfg)
        params = jax.tree.map(
            lambda p, sh: jax.device_put(p, sh), params, param_shardings
        )
        # opt state init under jit so mu/nu inherit param shardings
        opt = jax.jit(tx.init)(params)
        return {"params": params, "opt": opt, "step": jnp.zeros((), jnp.int32)}

    def shard_batch(batch):
        return jax.tree.map(lambda x: jax.device_put(x, batch_sharding), batch)

    return init_fn, step_fn, shard_batch, rules


def default_mesh_for_strategy(strategy: str, n_devices: int) -> MeshSpec:
    """Lay a strategy string onto n devices: each model-parallel axis
    named in the strategy (tp/sp/ep/pp) gets degree 2; the data axis
    (fsdp if named, else dp) absorbs the remainder. Pass an explicit
    MeshSpec (ScalingConfig.mesh) for non-default degrees."""
    parts = set(strategy.split("+")) if strategy else set()
    degrees = {}
    for ax in ("tp", "sp", "ep", "pp"):
        if ax in parts:
            degrees[ax] = 2
    data_axis = "fsdp" if "fsdp" in parts else "dp"
    degrees[data_axis] = -1  # absorb
    return MeshSpec(**degrees).resolve(n_devices)


def setup_sharded_training(
    cfg,
    strategy: Optional[str] = None,
    mesh_spec=None,
    devices=None,
    model=None,
    **step_kwargs,
):
    """Worker-loop entry: resolve the parallelism strategy (argument >
    the trainer's ScalingConfig.strategy, which JaxTrainer exports as
    RAY_TPU_TRAIN_STRATEGY > "fsdp"), build the mesh over this worker's
    visible devices, and return (mesh, init_fn, step_fn, shard_batch,
    rules).

    Usage inside a JaxTrainer train loop::

        mesh, init_fn, step_fn, shard_batch, _ = setup_sharded_training(cfg)
        state = init_fn(jax.random.PRNGKey(0))
        state, metrics = step_fn(state, shard_batch(batch))
    """
    import os

    strategy = strategy or os.environ.get("RAY_TPU_TRAIN_STRATEGY") or "fsdp"
    if devices is None:
        devices = jax.devices()
    # "dcn_dp=N+<inner>" routes to the multislice path: N device islands
    # with <inner> laid out inside each, gradients crossing islands via
    # the host-mediated DCN allreduce (parallel/multislice.py). Same
    # 5-tuple contract; state/batch become per-slice lists.
    if "dcn_dp" in strategy:
        parts = strategy.split("+")
        dcn = next(p for p in parts if p.startswith("dcn_dp"))
        n_slices = int(dcn.split("=")[1]) if "=" in dcn else 2
        inner = "+".join(p for p in parts if not p.startswith("dcn_dp")) or "dp"
        from ray_tpu.parallel.multislice import setup_multislice_training

        ms = setup_multislice_training(
            cfg, n_slices, strategy=inner, devices=devices, model=model, **step_kwargs
        )
        return ms.meshes, ms.init_states, ms.step, ms.shard_batches, ms.rules
    if mesh_spec is None:
        mesh_spec = default_mesh_for_strategy(strategy, len(devices))
    elif isinstance(mesh_spec, dict):
        mesh_spec = MeshSpec(**mesh_spec)
    mesh = build_mesh(mesh_spec, devices)
    init_fn, step_fn, shard_batch, rules = build_sharded_train_step(
        cfg, mesh, strategy=strategy, model=model, **step_kwargs
    )
    return mesh, init_fn, step_fn, shard_batch, rules
