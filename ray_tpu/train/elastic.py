"""Elastic gang recovery — re-gang only the failed rank.

The reference's failure semantics are restart-the-world: any worker
failure tears the gang down and every rank restarts from the last DISK
checkpoint (reference: train/_internal/backend_executor.py worker-group
teardown + FailureConfig(max_failures) retry; SURVEY §7 hard-part #6
sets the bar at better-than-reference). Elastic mode keeps the
surviving worker processes WARM — their jitted programs, device state
and python heap survive — replaces only the dead rank on its placement
bundle, and resumes from a survivor's IN-MEMORY state, no disk restore
and no cold compile on the survivors.

Protocol (generation-stamped lockstep barrier):

  - Elastic-aware train loops call `train.elastic_barrier(step, state=)`
    once per step. The call stamps the worker's latest state into its
    session (the in-memory checkpoint) and parks on the coordinator
    until every live rank reaches the same step.
  - When a rank dies, the trainer probes the gang, reads every
    survivor's (state, step) stamp, picks the MAX step as the resume
    point with its owner's state, starts a replacement actor on the
    dead rank's bundle with that state pre-loaded, and bumps the
    coordinator's generation.
  - Survivors wake (or arrive) with a generation mismatch -> resync:
    they keep their OWN state and step. A survivor that was still
    mid-step when the gang died trails the resume point by one; the
    coordinator's catch-up lane lets it run without parking until its
    step reaches the resume point, where lockstep resumes.
  - The replacement's first barrier consumes the pre-loaded state ->
    {"resync": True, "state": blob, "step": s}: it adopts the max-stamp
    survivor's state and joins at step s. Step count stays monotonic.
  - Only when EVERY rank is gone (or the loop never handed state to
    the barrier) does the trainer fall back to the reference-style
    full-restart path — which honors FailureConfig.max_failures, so
    with max_failures=0 the structural failure surfaces to the caller
    instead of restarting.

Loop contract (see tests/test_elastic.py)::

    while step < total:
        sig = train.elastic_barrier(step, state=state)
        if sig["resync"]:
            if sig["state"] is not None:      # replacement rank
                state, step = sig["state"], sig["step"]
            continue                          # survivors keep their own
        state = work(state); step += 1
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class ElasticCoordinator:
    """Generation-stamped lockstep barrier (async actor: parked calls
    cost nothing, the long-poll pattern of the serve controller)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self.gen = 0
        # -1 so the step-0 barrier parks normally; after a regang this is
        # the resume point and ranks FREE-RUN through it (see barrier)
        self.resume_step = -1
        self._waiters: Dict[int, Dict[str, Any]] = {}  # step -> {ranks, event}

    async def barrier(self, rank: int, gen: int, step: int) -> Dict[str, Any]:
        import asyncio

        if gen != self.gen:
            # stale generation: resync at the recorded resume step
            return {"gen": self.gen, "step": self.resume_step, "resync": True}
        if step <= self.resume_step:
            # free-run lane after a regang: ranks at or behind the resume
            # point proceed WITHOUT parking and lockstep re-engages at
            # resume+1. `<=` (not `<`) matters: a survivor that had
            # already finished the resume step's work rejoins at
            # resume+1, so a rank parking AT the resume step (the
            # replacement, or a survivor that hadn't started the work)
            # could otherwise wait for peers that will never come back
            # to that step — the all-survivors-mid-step deadlock.
            return {"gen": gen, "step": step, "resync": False}
        w = self._waiters.setdefault(step, {"ranks": set(), "event": asyncio.Event()})
        w["ranks"].add(rank)
        if len(w["ranks"]) >= self.world:
            self.resume_step = max(self.resume_step, step)
            w["event"].set()
            self._waiters.pop(step, None)
            return {"gen": gen, "step": step, "resync": False}
        my_gen = gen
        # ONE wait task per barrier call, cancelled on every exit path —
        # shielding a fresh wait() every 0.2s leaked a pending task per
        # poll forever after regang() cleared the waiters (the event of a
        # cleared waiter is never set, so those tasks could never finish)
        waiter = asyncio.ensure_future(w["event"].wait())
        try:
            while not waiter.done():
                if self.gen != my_gen:
                    # regang happened while parked: the step never completed
                    return {"gen": self.gen, "step": self.resume_step, "resync": True}
                await asyncio.wait({waiter}, timeout=0.2)
            if self.gen != my_gen:
                return {"gen": self.gen, "step": self.resume_step, "resync": True}
            return {"gen": my_gen, "step": step, "resync": False}
        finally:
            if not waiter.done():
                waiter.cancel()

    def regang(self, resume_step: int) -> int:
        """New generation resuming at `resume_step`; parked barriers wake
        with a mismatch and resync."""
        self.gen += 1
        self.resume_step = resume_step
        self._waiters.clear()
        return self.gen

    def state(self) -> Dict[str, Any]:
        return {"gen": self.gen, "resume_step": self.resume_step}


def elastic_barrier(step: int, state: Any = None) -> Dict[str, Any]:
    """Per-step gang sync for elastic-aware train loops.

    Stamps `state` as this worker's in-memory checkpoint, then blocks
    until every live rank reaches `step` (or a regang happens). Returns
    {"resync": bool, "state": blob-or-None, "step": int}: on resync the
    caller adopts `state` if given (replacement rank) and continues from
    `step`; otherwise proceeds with the step it proposed.
    """
    from ray_tpu.air.session import _get_session

    s = _get_session()
    if s is None:
        raise RuntimeError("elastic_barrier() called outside a training worker")
    if state is not None:
        s.elastic_state = state
        s.elastic_step = step
    resume = getattr(s, "elastic_resume", None)
    if resume is not None:
        # replacement rank: adopt the survivor's in-memory checkpoint
        s.elastic_resume = None
        s.elastic_state, s.elastic_step = resume
        return {"resync": True, "state": resume[0], "step": resume[1]}
    coord = getattr(s, "elastic_coord", None)
    if coord is None:
        return {"resync": False, "state": None, "step": step}
    resp = _bounded_barrier(coord, s.rank, s.elastic_gen, step)
    if resp.get("resync"):
        s.elastic_gen = resp["gen"]
        return {"resync": True, "state": None, "step": resp["step"]}
    return {"resync": False, "state": None, "step": step}


def _bounded_barrier(coord, rank: int, gen: int, step: int) -> Dict[str, Any]:
    """barrier() with a timeout + bounded retry, never an unbounded get.

    A parked barrier is NORMAL (peers may be slow, a regang may be in
    flight), so a per-attempt `ray_tpu.get` timeout is retried — the
    coordinator's waiter set is keyed by rank, making the re-issued
    call idempotent. What is NOT normal: a dead coordinator actor
    (raises immediately) or one that never answers across every retry
    (dead GCS / restarted coordinator the session still points at).
    Both surface as an actionable RuntimeError instead of hanging every
    rank forever. Knobs: RAY_TPU_ELASTIC_BARRIER_TIMEOUT_S (per
    attempt, default 60) and RAY_TPU_ELASTIC_BARRIER_RETRIES
    (default 10)."""
    import os

    from ray_tpu import exceptions

    timeout_s = float(os.environ.get("RAY_TPU_ELASTIC_BARRIER_TIMEOUT_S", "60"))
    retries = int(os.environ.get("RAY_TPU_ELASTIC_BARRIER_RETRIES", "10"))
    last_err: Optional[BaseException] = None
    for _ in range(max(1, retries)):
        try:
            return ray_tpu.get(
                coord.barrier.remote(rank, gen, step), timeout=timeout_s
            )
        except exceptions.GetTimeoutError as e:
            last_err = e
            continue
        except (exceptions.ActorError, exceptions.WorkerCrashedError) as e:
            raise RuntimeError(
                f"ElasticCoordinator died (rank {rank}, step {step}): the "
                "trainer must start a new coordinator and re-setup sessions "
                "before training can continue"
            ) from e
    raise RuntimeError(
        f"ElasticCoordinator barrier unanswered after {retries} x "
        f"{timeout_s:.0f}s (rank {rank}, step {step}) — the coordinator is "
        "hung or was restarted without a regang; raise "
        "RAY_TPU_ELASTIC_BARRIER_TIMEOUT_S if the gang legitimately parks "
        "longer than this"
    ) from last_err
