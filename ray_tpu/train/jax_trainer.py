"""JaxTrainer — distributed training over a TPU worker gang.

The replacement for the reference's TorchTrainer stack
(reference: TorchTrainer at python/ray/train/torch/torch_trainer.py:208;
DataParallelTrainer at train/data_parallel_trainer.py; BackendExecutor at
train/_internal/backend_executor.py:65 — placement group :200,
start_training :438; NCCL process-group setup at train/torch/config.py:47-99).

What changes TPU-side:
  - No process groups / NCCL: each worker is a host actor owning its
    chips; multi-host SPMD is initialized with jax.distributed via
    GCS-KV rendezvous (ray_tpu.parallel.initialize_multihost) and all
    collectives are XLA ICI ops from sharding annotations.
  - The gang is a placement group whose bundles map to pod-slice hosts
    (ScalingConfig.topology → tpu_slice_bundles).
  - Failure handling follows the reference's semantics: any worker
    failure tears down the gang and retries from the last checkpoint up
    to FailureConfig.max_failures.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train._internal import storage
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.metrics import metric_singletons
from ray_tpu.util.queue import Queue

logger = logging.getLogger("ray_tpu.train")


def _train_metrics_factory():
    from ray_tpu.util import metrics

    return dict(
        report=metrics.Gauge(
            "ray_tpu_train_report",
            "latest rank-0 train.report() metrics", tag_keys=("metric",)),
    )


_train_metrics = metric_singletons(_train_metrics_factory)


def _publish_train_report(item: Dict[str, Any]) -> None:
    """Rank-0 report → live training telemetry: numeric metrics become
    gauges on /metrics (tagged by name) and the latest report joins the
    /api/training snapshot, alongside any step-telemetry MFU/goodput the
    worker's instrumented step_fn already flushes. Best-effort — a
    telemetry hiccup must never fail training."""
    try:
        from ray_tpu import observability

        numeric = {}
        for k, v in (item.get("metrics") or {}).items():
            try:
                numeric[k] = float(v)
            except (TypeError, ValueError):
                continue
        g = _train_metrics()["report"]
        for k, v in numeric.items():
            g.set(v, tags={"metric": k})
        # the GCS push is a sync round-trip: throttle it so a loop
        # reporting every step can't stall the result-draining loop
        now = time.monotonic()
        if now - _publish_train_report._t_last >= 0.5:
            _publish_train_report._t_last = now
            observability.publish_snapshot(
                "training",
                {"iteration": item.get("iteration"), "report": numeric},
            )
    except Exception:
        pass


_publish_train_report._t_last = -1e9


class Result:
    """reference: python/ray/air/result.py."""

    def __init__(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint], path: str, error=None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error

    def __repr__(self):
        return f"Result(metrics={self.metrics}, checkpoint={self.checkpoint}, error={self.error})"


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume = resume_from_checkpoint

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        run_dir = storage.make_run_dir(self.run_config.storage_path, self.run_config.name)
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        restore = self._resume.path if self._resume else None
        while True:
            try:
                return self._fit_once(run_dir, restore)
            except Exception as e:
                attempt += 1
                if attempt > max_failures >= 0:
                    if max_failures == 0:
                        raise
                    logger.exception("training failed after %d retries", attempt - 1)
                    last = storage.latest_checkpoint(run_dir)
                    return Result(
                        metrics={},
                        checkpoint=Checkpoint(last) if last else None,
                        path=run_dir,
                        error=e,
                    )
                restore = storage.latest_checkpoint(run_dir) or restore
                logger.warning(
                    "worker gang failed (%s); retry %d/%d from %s", e, attempt, max_failures, restore
                )

    def _fit_once(self, run_dir: str, restore: Optional[str]) -> Result:
        sc = self.scaling_config
        cc: CheckpointConfig = self.run_config.checkpoint_config
        elastic = getattr(self.run_config.failure_config, "elastic", False)
        results_q = Queue()
        env = {}
        if sc.use_tpu:
            env["RAY_TPU_TRAIN_STRATEGY"] = sc.strategy
        group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy,
            env=env,
            # a second slot lets get_elastic_state answer while the
            # train loop is parked inside the barrier call
            max_concurrency=2 if elastic else 1,
        )
        coord = None
        if elastic:
            from ray_tpu.train.elastic import ElasticCoordinator

            coord = ElasticCoordinator.remote(sc.num_workers)
        try:
            ray_tpu.get(
                [
                    w.setup_session.remote(results_q, run_dir, restore, coord,
                                           None, 0, cc)
                    for w in group.workers
                ]
            )
            config = dict(self._config)
            if self._datasets:
                config["datasets"] = self._datasets
            done_refs = group.run_all(self._train_loop, config)

            last_metrics: Dict[str, Any] = {}
            last_ckpt: Optional[str] = None
            # rank per pending ref so elastic recovery can identify the
            # dead rank from its failed run() ref
            pending: Dict[Any, int] = {ref: i for i, ref in enumerate(done_refs)}
            gen = 0
            while pending:
                ready, _ = ray_tpu.wait(
                    list(pending), num_returns=len(pending), timeout=0.25
                )
                for ref in ready:
                    # a prior regang's death probe may have removed this
                    # ref already (two ranks dying in one wait round)
                    rank = pending.pop(ref, None)
                    if rank is None:
                        continue
                    try:
                        ray_tpu.get(ref)  # surface worker exceptions
                    except (ray_tpu.exceptions.ActorError,
                            ray_tpu.exceptions.WorkerCrashedError):
                        # actor/process DEATH — the elastic case. An
                        # application exception from the user loop is NOT:
                        # respawning would just re-raise it forever, so it
                        # propagates like the non-elastic path.
                        if not elastic:
                            raise
                        gen = self._elastic_regang(
                            group, coord, results_q, run_dir, restore, rank,
                            pending, config, gen,
                        )
                while True:
                    try:
                        item = results_q.get(block=False)
                    except Exception:
                        break
                    if item["rank"] == 0:
                        last_metrics = item["metrics"]
                        _publish_train_report(item)
                        if item.get("checkpoint"):
                            last_ckpt = item["checkpoint"]
                            storage.prune_checkpoints(run_dir, cc.num_to_keep)
            # drain any remaining reports
            while True:
                try:
                    item = results_q.get(block=False)
                except Exception:
                    break
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    _publish_train_report(item)
                    if item.get("checkpoint"):
                        last_ckpt = item["checkpoint"]
                        storage.prune_checkpoints(run_dir, cc.num_to_keep)
            ckpt = Checkpoint(last_ckpt) if last_ckpt else None
            return Result(metrics=last_metrics, checkpoint=ckpt, path=run_dir)
        finally:
            try:
                results_q.shutdown()
            except Exception:
                pass
            group.shutdown()
            if coord is not None:
                try:
                    ray_tpu.kill(coord)
                except Exception:
                    pass

    def _elastic_regang(self, group, coord, results_q, run_dir, restore, dead_rank,
                        pending, config, gen) -> int:
        """Replace ONE dead rank with the survivors kept warm
        (train/elastic.py; SURVEY §7 hard-part #6 — the bar is better
        than the reference's restart-the-world)."""
        # probe the rest of the gang: more ranks may have died with it
        dead = {dead_rank}
        for ref, rank in list(pending.items()):
            try:
                ray_tpu.get(group.workers[rank].ping.remote(), timeout=10)
            except Exception:
                dead.add(rank)
                pending.pop(ref)
        if len(dead) >= group.num_workers:
            raise RuntimeError("entire gang lost — falling back to full restart")
        # resume point = MAX stamp across survivors (a survivor mid-step
        # at death time trails by one and catches up through the
        # coordinator's catch-up lane); state comes from the max-stamp
        # owner so the replacement joins exactly at the resume point
        survivors = [i for i in range(group.num_workers) if i not in dead]
        stamps = ray_tpu.get(
            [group.workers[i].get_elastic_state.remote() for i in survivors],
            timeout=60,
        )
        best = max(range(len(survivors)), key=lambda j: stamps[j][1])
        survivor = survivors[best]
        state, step = stamps[best]
        if state is None:
            # the loop never handed state to elastic_barrier: there is no
            # in-memory checkpoint to resume the replacement from — fall
            # back to the full-restart path (disk checkpoint)
            raise RuntimeError(
                "elastic recovery needs the train loop to pass state= to "
                "train.elastic_barrier(); falling back to full restart"
            )
        logger.warning(
            "elastic re-gang: rank(s) %s died at step ~%d; survivors stay warm, "
            "resuming from rank %d's in-memory state", sorted(dead), step, survivor,
        )
        gen = ray_tpu.get(coord.regang.remote(step))
        for r in sorted(dead):
            w = group.replace_worker(r)
            ray_tpu.get(
                w.setup_session.remote(
                    results_q, run_dir, restore, coord,
                    (state, step), gen, self.run_config.checkpoint_config,
                )
            )
            pending[w.run.remote(self._train_loop, config)] = r
        return gen

    @classmethod
    def restore(cls, path: str, train_loop_per_worker: Callable, **kwargs) -> "JaxTrainer":
        """reference: BaseTrainer.restore (train/base_trainer.py:218)."""
        last = storage.latest_checkpoint(path)
        trainer = cls(train_loop_per_worker, **kwargs)
        if last:
            trainer._resume = Checkpoint(last)
        if trainer.run_config.name is None:
            import os

            trainer.run_config.name = os.path.basename(path)
            trainer.run_config.storage_path = os.path.dirname(path)
        return trainer


class DataParallelTrainer(JaxTrainer):
    """Parity alias (reference: train/data_parallel_trainer.py)."""
