"""JaxTrainer — distributed training over a TPU worker gang.

The replacement for the reference's TorchTrainer stack
(reference: TorchTrainer at python/ray/train/torch/torch_trainer.py:208;
DataParallelTrainer at train/data_parallel_trainer.py; BackendExecutor at
train/_internal/backend_executor.py:65 — placement group :200,
start_training :438; NCCL process-group setup at train/torch/config.py:47-99).

What changes TPU-side:
  - No process groups / NCCL: each worker is a host actor owning its
    chips; multi-host SPMD is initialized with jax.distributed via
    GCS-KV rendezvous (ray_tpu.parallel.initialize_multihost) and all
    collectives are XLA ICI ops from sharding annotations.
  - The gang is a placement group whose bundles map to pod-slice hosts
    (ScalingConfig.topology → tpu_slice_bundles).
  - Failure handling follows the reference's semantics: any worker
    failure tears down the gang and retries from the last checkpoint up
    to FailureConfig.max_failures.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train._internal import storage
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.queue import Queue

logger = logging.getLogger("ray_tpu.train")


class Result:
    """reference: python/ray/air/result.py."""

    def __init__(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint], path: str, error=None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error

    def __repr__(self):
        return f"Result(metrics={self.metrics}, checkpoint={self.checkpoint}, error={self.error})"


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume = resume_from_checkpoint

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        run_dir = storage.make_run_dir(self.run_config.storage_path, self.run_config.name)
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        restore = self._resume.path if self._resume else None
        while True:
            try:
                return self._fit_once(run_dir, restore)
            except Exception as e:
                attempt += 1
                if attempt > max_failures >= 0:
                    if max_failures == 0:
                        raise
                    logger.exception("training failed after %d retries", attempt - 1)
                    last = storage.latest_checkpoint(run_dir)
                    return Result(
                        metrics={},
                        checkpoint=Checkpoint(last) if last else None,
                        path=run_dir,
                        error=e,
                    )
                restore = storage.latest_checkpoint(run_dir) or restore
                logger.warning(
                    "worker gang failed (%s); retry %d/%d from %s", e, attempt, max_failures, restore
                )

    def _fit_once(self, run_dir: str, restore: Optional[str]) -> Result:
        sc = self.scaling_config
        cc: CheckpointConfig = self.run_config.checkpoint_config
        results_q = Queue()
        env = {}
        if sc.use_tpu:
            env["RAY_TPU_TRAIN_STRATEGY"] = sc.strategy
        group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy,
            env=env,
        )
        try:
            ray_tpu.get(
                [
                    w.setup_session.remote(results_q, run_dir, restore)
                    for w in group.workers
                ]
            )
            config = dict(self._config)
            if self._datasets:
                config["datasets"] = self._datasets
            done_refs = group.run_all(self._train_loop, config)

            last_metrics: Dict[str, Any] = {}
            last_ckpt: Optional[str] = None
            pending = list(done_refs)
            while pending:
                ready, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.25)
                if ready:
                    # surface worker exceptions
                    ray_tpu.get(ready)
                while True:
                    try:
                        item = results_q.get(block=False)
                    except Exception:
                        break
                    if item["rank"] == 0:
                        last_metrics = item["metrics"]
                        if item.get("checkpoint"):
                            last_ckpt = item["checkpoint"]
                            storage.prune_checkpoints(run_dir, cc.num_to_keep)
            # drain any remaining reports
            while True:
                try:
                    item = results_q.get(block=False)
                except Exception:
                    break
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    if item.get("checkpoint"):
                        last_ckpt = item["checkpoint"]
                        storage.prune_checkpoints(run_dir, cc.num_to_keep)
            ckpt = Checkpoint(last_ckpt) if last_ckpt else None
            return Result(metrics=last_metrics, checkpoint=ckpt, path=run_dir)
        finally:
            try:
                results_q.shutdown()
            except Exception:
                pass
            group.shutdown()

    @classmethod
    def restore(cls, path: str, train_loop_per_worker: Callable, **kwargs) -> "JaxTrainer":
        """reference: BaseTrainer.restore (train/base_trainer.py:218)."""
        last = storage.latest_checkpoint(path)
        trainer = cls(train_loop_per_worker, **kwargs)
        if last:
            trainer._resume = Checkpoint(last)
        if trainer.run_config.name is None:
            import os

            trainer.run_config.name = os.path.basename(path)
            trainer.run_config.storage_path = os.path.dirname(path)
        return trainer


class DataParallelTrainer(JaxTrainer):
    """Parity alias (reference: train/data_parallel_trainer.py)."""
