"""Deterministic preemption injection for elastic-training tests/bench.

The implementation moved to ``ray_tpu/chaos.py`` when the serving plane
grew its own fault injection — seeded kill/hang/slow schedules are now
ONE shared module covering both step-keyed training faults (these
re-exports) and time-keyed serve replica chaos
(``chaos.ChaosSchedule`` / ``chaos.ServeChaosInjector``). This shim
keeps every existing train import path working unchanged.
"""
from __future__ import annotations

from ray_tpu.chaos import (  # noqa: F401
    FaultEvent,
    PreemptionInjector,
    PreemptionSchedule,
    SlicePreempted,
)

__all__ = [
    "FaultEvent",
    "PreemptionInjector",
    "PreemptionSchedule",
    "SlicePreempted",
]
