"""Deterministic preemption injection for elastic-training tests/bench.

TPU slices get preempted: spot reclaims, maintenance events, link
flaps. The chaos tier (tests/test_chaos.py) kills PROCESSES at random;
this module injects SLICE-level faults into `MultisliceTrainStep` on a
seeded, perfectly replayable schedule, so an elastic run's
degrade → re-admit behavior (and its goodput bill) is a deterministic
function of (seed, config) — the property the regression tests and
`bench.py`'s elastic section both lean on.

Three fault kinds, mirroring how real slices fail:

  kill — the slice vanishes mid-step (spot reclaim). Raises
         `SlicePreempted` from inside the slice's work; the slice stays
         dead for `duration_steps`, then becomes re-admittable.
  hang — the slice stops responding but the process lives (wedged ICI,
         driver stall). The injected work sleeps past the trainer's
         probe timeout so detection happens via the BOUNDED-TIMEOUT
         probe path, not an exception.
  slow — a straggler (thermal throttle, noisy neighbor): work is
         delayed by `slow_s` but completes. No membership change —
         goodput erodes without a recovery event.

Kills can carry an ADVANCE MAINTENANCE NOTICE (`notice_steps > 0`),
modeling TPU maintenance-event warnings: `maintenance_notice(step)`
reports the impending kill before it fires so the train loop can take
a PRIORITY checkpoint while the slice is still healthy.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple


class SlicePreempted(Exception):
    """A slice died (or was declared dead) mid-step."""

    def __init__(self, slice_idx: int, kind: str = "kill"):
        super().__init__(f"slice {slice_idx} preempted ({kind})")
        self.slice_idx = slice_idx
        self.kind = kind


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    step: int            # first step the fault is active
    slice_idx: int
    kind: str            # "kill" | "hang" | "slow"
    duration_steps: int = 3   # steps the slice stays down (kill/hang)
    notice_steps: int = 0     # advance maintenance notice before a kill
    slow_s: float = 0.0       # extra latency for "slow"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @property
    def end_step(self) -> int:
        return self.step + self.duration_steps


class PreemptionSchedule:
    """An ordered, replayable list of FaultEvents."""

    def __init__(self, events: Sequence[FaultEvent], seed: Optional[int] = None):
        self.events: List[FaultEvent] = sorted(
            events, key=lambda e: (e.step, e.slice_idx)
        )
        self.seed = seed

    @classmethod
    def generate(
        cls,
        seed: int,
        n_slices: int,
        total_steps: int,
        *,
        n_events: int = 2,
        kinds: Sequence[str] = ("kill", "hang", "slow"),
        min_gap_steps: int = 6,
        duration_steps: Tuple[int, int] = (2, 4),
        notice_prob: float = 0.5,
        notice_steps: int = 2,
        slow_s: float = 0.05,
    ) -> "PreemptionSchedule":
        """Deterministic in (seed, args): same inputs, same schedule.
        Events never target slice 0 (one survivor must always hold the
        authoritative state to broadcast from) and are spaced at least
        `min_gap_steps` apart so each outage resolves before the next."""
        import numpy as np

        if n_slices < 2:
            return cls([], seed=seed)
        rng = np.random.Generator(np.random.PCG64(seed))
        events: List[FaultEvent] = []
        step = int(rng.integers(min_gap_steps, max(min_gap_steps + 1, total_steps // 3)))
        for _ in range(n_events):
            if step >= total_steps - 1:
                break
            kind = str(rng.choice(list(kinds)))
            dur = int(rng.integers(duration_steps[0], duration_steps[1] + 1))
            notice = (
                notice_steps
                if kind == "kill" and rng.random() < notice_prob
                else 0
            )
            events.append(
                FaultEvent(
                    step=step,
                    slice_idx=int(rng.integers(1, n_slices)),
                    kind=kind,
                    duration_steps=dur if kind != "slow" else 0,
                    notice_steps=notice,
                    slow_s=slow_s if kind == "slow" else 0.0,
                )
            )
            step += dur + int(rng.integers(min_gap_steps, 2 * min_gap_steps))
        return cls(events, seed=seed)

    # ---------------------------------------------------------- replay io
    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "events": [e.to_dict() for e in self.events]}
        )

    @classmethod
    def from_json(cls, blob: str) -> "PreemptionSchedule":
        d = json.loads(blob)
        return cls([FaultEvent(**e) for e in d["events"]], seed=d.get("seed"))

    def __eq__(self, other) -> bool:
        return isinstance(other, PreemptionSchedule) and self.events == other.events

    def __repr__(self) -> str:
        return f"PreemptionSchedule(seed={self.seed}, events={self.events})"


class PreemptionInjector:
    """Drives a schedule against a MultisliceTrainStep.

    The trainer calls `check(slice_idx, step)` inside each slice's
    work, `maintenance_notice(step)` before dispatching a step, and
    `revivable(step)` when deciding whether to re-admit. `hang_s`
    bounds the simulated hang so test threads eventually unwind — it
    must exceed the trainer's probe timeout for the hang to be
    DETECTED as one."""

    def __init__(self, schedule: PreemptionSchedule, *, hang_s: float = 2.0):
        self.schedule = schedule
        self.hang_s = hang_s
        self.fired: List[FaultEvent] = []
        self._down: Dict[int, FaultEvent] = {}  # slice -> active outage

    # ---------------------------------------------------------- queries
    def maintenance_notice(self, step: int) -> List[FaultEvent]:
        """Kills whose advance-notice window covers `step` and have not
        fired yet — the signal for a priority checkpoint."""
        return [
            e
            for e in self.schedule.events
            if e.kind == "kill"
            and e.notice_steps > 0
            and e.step - e.notice_steps <= step < e.step
        ]

    def active_event(self, slice_idx: int, step: int) -> Optional[FaultEvent]:
        for e in self.schedule.events:
            if e.slice_idx != slice_idx:
                continue
            if e.kind == "slow" and e.step == step:
                return e
            if e.kind in ("kill", "hang") and e.step <= step < e.end_step:
                return e
        return None

    def revivable(self, step: int) -> Set[int]:
        """Slices whose outage has ended by `step` (ready to re-admit)."""
        out = set()
        for e in self.schedule.events:
            if e.kind in ("kill", "hang") and e.end_step <= step:
                out.add(e.slice_idx)
        # minus slices currently inside a LATER outage
        for e in self.schedule.events:
            if e.kind in ("kill", "hang") and e.step <= step < e.end_step:
                out.discard(e.slice_idx)
        return out

    # ------------------------------------------------------------ inject
    def check(self, slice_idx: int, step: int) -> None:
        """Called inside a slice's per-step work. Raises/sleeps per the
        schedule; a no-op for healthy (slice, step) pairs."""
        e = self.active_event(slice_idx, step)
        if e is None:
            return
        if e not in self.fired:
            self.fired.append(e)
        if e.kind == "kill":
            raise SlicePreempted(slice_idx, "kill")
        if e.kind == "hang":
            # wedge past the probe timeout, then die like the probe
            # would eventually observe — bounded so threads unwind
            time.sleep(self.hang_s)
            raise SlicePreempted(slice_idx, "hang")
        if e.kind == "slow":
            time.sleep(e.slow_s)
