"""ray_tpu.train — distributed training (reference: python/ray/train)."""
from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.session import (  # noqa: F401
    get_checkpoint,
    get_checkpoint_manager,
    get_context,
    report,
)
from ray_tpu.train.checkpoint_manager import CheckpointManager  # noqa: F401
from ray_tpu.train.elastic import elastic_barrier  # noqa: F401
from ray_tpu.train.fault_injection import (  # noqa: F401
    FaultEvent,
    PreemptionInjector,
    PreemptionSchedule,
    SlicePreempted,
)
from ray_tpu.train.goodput import GoodputMeter  # noqa: F401
from ray_tpu.train.jax_trainer import DataParallelTrainer, JaxTrainer, Result  # noqa: F401
from ray_tpu.train.step import (  # noqa: F401
    build_sharded_train_step,
    default_mesh_for_strategy,
    setup_sharded_training,
)
