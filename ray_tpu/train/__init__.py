"""ray_tpu.train — distributed training (reference: python/ray/train)."""
from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.session import get_checkpoint, get_context, report  # noqa: F401
from ray_tpu.train.elastic import elastic_barrier  # noqa: F401
from ray_tpu.train.jax_trainer import DataParallelTrainer, JaxTrainer, Result  # noqa: F401
from ray_tpu.train.step import (  # noqa: F401
    build_sharded_train_step,
    default_mesh_for_strategy,
    setup_sharded_training,
)
