"""Async checkpoint manager — checkpointing that never blocks the step.

CheckFreq (Mohan et al., FAST '21) splits a checkpoint into a cheap
synchronous SNAPSHOT and an expensive asynchronous PERSIST, pipelining
the write behind subsequent training steps. This manager is that split
for sharded jax train state:

  - `save(step, state)` captures the state to HOST memory (D2H, the
    only part the train loop ever waits for — call it right after the
    next step is dispatched so the copy overlaps device compute), then
    hands the write to a background thread and returns.
  - The writer thread persists with the atomic commit protocol from
    `train/_internal/storage.py`: payload into a `.tmp-` dir, COMMIT
    marker, `os.rename` to the final `checkpoint_XXXXXX` name. A
    process SIGKILLed at ANY point leaves either a committed previous
    checkpoint or an ignorable tmp dir — `latest_checkpoint()` can
    never resolve to a torn write.
  - At-most-one-save-in-flight backpressure: a `save()` arriving while
    a write is still running is SKIPPED (counted in `stats()`), so a
    slow filesystem degrades checkpoint frequency instead of stacking
    host copies of the whole model. `priority=True` (the maintenance-
    notice path: a preemption is coming and THIS state must land) waits
    for the in-flight write and then saves.
  - Retention pruning keeps the newest `num_to_keep` committed
    checkpoints; uncommitted garbage never counts against the budget.

Payload formats: "orbax" (zarr, sharded-friendly — default when orbax
imports) or "numpy" (flat npz — zero extra deps, used by tests and as
the automatic fallback).
"""
from __future__ import annotations

import json
import os
import queue
import shutil
import signal
import threading
import time
import uuid
from typing import Any, Dict, Optional, Tuple

from ray_tpu.train._internal import storage

_PAYLOAD_SUBDIR = "state"
_LEAF_KEY = "leaf_{:05d}"

# test hook: crash the WRITER at a named protocol point
# ("after_payload" = between tmp-write and commit marker,
#  "after_marker" = between marker and rename)
_CRASH_ENV = "RAY_TPU_CKPT_TEST_CRASH"


def _maybe_crash(point: str) -> None:
    if os.environ.get(_CRASH_ENV) == point:
        os.kill(os.getpid(), signal.SIGKILL)


def _host_snapshot(state: Any) -> Any:
    """D2H copy of every leaf (blocks until the arrays are computed —
    the snapshot cost save() reports as its stall)."""
    import jax
    import numpy as np

    return jax.tree.map(lambda x: np.asarray(x), state)


def _write_numpy(payload_dir: str, host_state: Any) -> None:
    import jax
    import numpy as np

    leaves, _ = jax.tree.flatten(host_state)
    os.makedirs(payload_dir, exist_ok=True)
    arrays = {_LEAF_KEY.format(i): np.asarray(l) for i, l in enumerate(leaves)}
    # savez to a tmp name then rename: np.savez is not atomic either
    tmp = os.path.join(payload_dir, f".leaves.{uuid.uuid4().hex[:8]}.npz")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(payload_dir, "leaves.npz"))


def _read_numpy(payload_dir: str, target: Any = None) -> Any:
    import jax
    import numpy as np

    with np.load(os.path.join(payload_dir, "leaves.npz")) as z:
        arrays = [z[_LEAF_KEY.format(i)] for i in range(len(z.files))]
    if target is None:
        return arrays
    t_leaves, treedef = jax.tree.flatten(target)
    if len(t_leaves) != len(arrays):
        raise ValueError(
            f"checkpoint has {len(arrays)} leaves, target has {len(t_leaves)}"
        )
    return jax.tree.unflatten(treedef, arrays)


def _write_orbax(payload_dir: str, host_state: Any) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    # orbax owns payload_dir's final contents: write to its own tmp
    # sibling and rename so foreign files never mix into the zarr tree
    tmp = f"{payload_dir}.ocp-{uuid.uuid4().hex[:8]}"
    ckptr.save(tmp, host_state, force=True)
    # PyTreeCheckpointer is synchronous on older orbax (no drain method)
    getattr(ckptr, "wait_until_finished", lambda: None)()
    os.rename(tmp, payload_dir)


def _read_orbax(payload_dir: str, target: Any = None) -> Any:
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    if target is None:
        return ckptr.restore(payload_dir)
    import jax
    import numpy as np

    host_target = jax.tree.map(lambda x: np.asarray(x), target)
    return ckptr.restore(payload_dir, item=host_target)


_WRITERS = {"numpy": (_write_numpy, _read_numpy), "orbax": (_write_orbax, _read_orbax)}


def _resolve_format(fmt: str) -> str:
    if fmt != "auto":
        return fmt
    try:
        import orbax.checkpoint  # noqa: F401

        return "orbax"
    except Exception:
        return "numpy"


class CheckpointManager:
    """Async, atomic, pruned checkpointing for one run directory.

    Typical elastic train loop::

        mgr = CheckpointManager(run_dir, num_to_keep=3, checkpoint_interval=50)
        restored = mgr.restore(target=state)
        if restored is not None:
            state, start_step = restored[0], restored[1] + 1
        for step in range(start_step, total):
            state, metrics = step_fn(state, batch)   # dispatched async
            mgr.maybe_save(step, state)              # snapshot + return
        mgr.wait()                                   # drain before exit
    """

    def __init__(
        self,
        run_dir: str,
        *,
        async_save: bool = True,
        num_to_keep: Optional[int] = None,
        checkpoint_interval: int = 0,
        fmt: str = "auto",
        goodput_meter=None,
    ):
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.async_save = async_save
        self.num_to_keep = num_to_keep
        self.checkpoint_interval = int(checkpoint_interval)
        self.fmt = _resolve_format(fmt)
        self._meter = goodput_meter
        storage.sweep_stale_tmp_dirs(self.run_dir)

        self._lock = threading.Lock()
        self._inflight: Optional[threading.Event] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stats: Dict[str, Any] = {
            "saves": 0,
            "skipped_inflight": 0,
            "failures": 0,
            "last_stall_ms": 0.0,
            "total_stall_ms": 0.0,
            "last_write_ms": 0.0,
            "last_saved_step": None,
        }

    # ------------------------------------------------------------ worker
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True, name="ckpt-writer"
            )
            self._thread.start()

    def _writer_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            step, host_state, done = job
            t0 = time.perf_counter()
            try:
                self._write_checkpoint(step, host_state)
                with self._lock:
                    self._stats["saves"] += 1
                    self._stats["last_saved_step"] = step
                    self._stats["last_write_ms"] = (time.perf_counter() - t0) * 1e3
            except Exception:
                with self._lock:
                    self._stats["failures"] += 1
            finally:
                done.set()
                with self._lock:
                    if self._inflight is done:
                        self._inflight = None

    def _write_checkpoint(self, step: int, host_state: Any) -> None:
        """The full commit protocol, crash-hookable at every seam."""
        final = os.path.join(self.run_dir, f"checkpoint_{step:06d}")
        tmp = f"{final}{storage._TMP_INFIX}{os.getpid()}-{uuid.uuid4().hex[:8]}"
        os.makedirs(tmp)
        try:
            write_fn, _ = _WRITERS[self.fmt]
            write_fn(os.path.join(tmp, _PAYLOAD_SUBDIR), host_state)
            _maybe_crash("after_payload")
            storage.write_commit_marker(tmp, {"step": step, "format": self.fmt})
            _maybe_crash("after_marker")
            aside = None
            if os.path.isdir(final):
                # re-save of the same step: the old dir moves aside (tmp
                # name → reapable) only for the instant between the two
                # renames, and is deleted only after the new dir holds
                # the final name — older checkpoints stay committed
                # throughout, so a SIGKILL here costs at most this one
                # step's dir, never the run's restorability
                aside = f"{final}{storage._TMP_INFIX}replaced-{uuid.uuid4().hex[:8]}"
                os.rename(final, aside)
            os.rename(tmp, final)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        storage.prune_checkpoints(self.run_dir, self.num_to_keep)

    # -------------------------------------------------------------- save
    def save(self, step: int, state: Any, *, priority: bool = False,
             block: Optional[bool] = None) -> bool:
        """Snapshot `state` to host and persist it as checkpoint `step`.

        Returns False when skipped by the at-most-one-in-flight
        backpressure (never for priority saves). `block` overrides the
        manager's async_save default; even a blocking save runs the
        writer on the background thread — the caller just waits — so
        the hot path has exactly one code shape to lint.
        """
        block = (not self.async_save) if block is None else block
        with self._lock:
            inflight = self._inflight
        if inflight is not None and not inflight.is_set():
            if not priority:
                with self._lock:
                    self._stats["skipped_inflight"] += 1
                return False
            inflight.wait()

        t0 = time.perf_counter()
        host_state = _host_snapshot(state)
        stall_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._stats["last_stall_ms"] = stall_ms
            self._stats["total_stall_ms"] += stall_ms
        if self._meter is not None:
            try:
                self._meter.add_lost("checkpoint_stall", stall_ms / 1e3)
            except Exception:
                pass

        done = threading.Event()
        with self._lock:
            self._inflight = done
        self._ensure_thread()
        self._queue.put((int(step), host_state, done))
        if block:
            done.wait()
        return True

    def maybe_save(self, step: int, state: Any, *, priority: bool = False) -> bool:
        """save() gated on the configured `checkpoint_interval`
        (CheckpointConfig.checkpoint_interval; 0 = never automatic) —
        the train-loop one-liner. A priority save (maintenance notice)
        always goes through regardless of the interval."""
        if priority or (
            self.checkpoint_interval and step % self.checkpoint_interval == 0
        ):
            return self.save(step, state, priority=priority)
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the in-flight write (if any) completes."""
        with self._lock:
            inflight = self._inflight
        if inflight is None:
            return True
        return inflight.wait(timeout)

    def close(self) -> None:
        self.wait()
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=5)

    # ----------------------------------------------------------- restore
    def latest_checkpoint(self) -> Optional[str]:
        """Newest committed checkpoint dir (skips uncommitted/corrupt)."""
        return storage.latest_checkpoint(self.run_dir)

    def latest_step(self) -> Optional[int]:
        path = self.latest_checkpoint()
        if path is None:
            return None
        meta = storage.read_commit_meta(path) or {}
        if "step" in meta:
            return int(meta["step"])
        try:
            return int(os.path.basename(path).split("_")[-1])
        except ValueError:
            return None

    def restore(self, target: Any = None) -> Optional[Tuple[Any, int]]:
        """(state, step) from the newest manager-readable checkpoint,
        or None.

        The checkpoint is resolved ONCE and its step read from that
        same dir's marker (re-resolving could race a background commit
        landing in between — state from one checkpoint with a newer
        one's step number). Checkpoints in the run dir that this
        manager didn't write (e.g. `session.report` ingests — no
        payload subdir, foreign format) are skipped in favor of the
        newest one it can actually read.

        With `target` given, the loaded host arrays are placed back
        onto `target`'s shardings (H2D) so the state resumes exactly
        where the sharded train step expects it.
        """
        path = host_state = meta = None
        for cand in reversed(storage.list_checkpoints(self.run_dir)):
            meta = storage.read_commit_meta(cand) or {}
            fmt = meta.get("format", self.fmt)
            payload = os.path.join(cand, _PAYLOAD_SUBDIR)
            if fmt not in _WRITERS or not os.path.isdir(payload):
                continue
            _, read_fn = _WRITERS[fmt]
            host_state = read_fn(payload, target)
            path = cand
            break
        if path is None:
            return None
        if "step" in meta:
            step = int(meta["step"])
        else:
            try:
                step = int(os.path.basename(path).split("_")[-1])
            except ValueError:
                step = 0
        if target is None:
            return host_state, step
        import jax

        def _place(loaded, like):
            sharding = getattr(like, "sharding", None)
            if sharding is not None:
                return jax.device_put(loaded, sharding)
            return loaded

        return jax.tree.map(_place, host_state, target), step

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
            out["save_in_flight"] = (
                self._inflight is not None and not self._inflight.is_set()
            )
        out["format"] = self.fmt
        out["async_save"] = self.async_save
        return out

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def latest_checkpoint(run_dir: str) -> Optional[str]:
    """Module-level convenience mirroring storage.latest_checkpoint."""
    return storage.latest_checkpoint(run_dir)
