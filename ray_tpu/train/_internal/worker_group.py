"""Training worker group — the gang of host actors.

Equivalent of the reference's WorkerGroup
(reference: python/ray/train/_internal/worker_group.py:102). Each worker
is an actor pinned to a placement-group bundle; on TPU pods one worker
per host owns that host's chips.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    """Hosts one rank of the training gang."""

    def __init__(self, rank: int, world_size: int, env: Optional[Dict[str, str]] = None):
        self.rank = rank
        self.world_size = world_size
        for k, v in (env or {}).items():
            os.environ[k] = v

    def setup_session(self, result_queue, storage_dir: str, restore_checkpoint: Optional[str],
                      elastic_coord=None, elastic_resume=None, elastic_gen: int = 0,
                      checkpoint_config=None):
        from ray_tpu.air.session import _Session, _set_session

        self._session = _Session(
            rank=self.rank,
            world_size=self.world_size,
            local_rank=self.rank,
            result_queue=result_queue,
            storage_dir=storage_dir,
            restore_checkpoint=restore_checkpoint,
            elastic_coord=elastic_coord,
            elastic_resume=elastic_resume,
            elastic_gen=elastic_gen,
            checkpoint_config=checkpoint_config,
        )
        _set_session(self._session)
        return True

    def get_elastic_state(self):
        """(latest in-memory state stamp, its step) — served on a second
        concurrency slot while the train loop is parked in the barrier."""
        s = self._session
        return s.elastic_state, s.elastic_step

    def run(self, fn: Callable, config: Optional[Dict[str, Any]] = None):
        from ray_tpu.air.session import _set_session

        _set_session(self._session)
        import inspect

        if config is not None or len(inspect.signature(fn).parameters) >= 1:
            return fn(config or {})
        return fn()

    def ping(self):
        return self.rank

    def node_id(self):
        return ray_tpu.get_runtime_context().node_id


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        env: Optional[Dict[str, str]] = None,
        max_concurrency: int = 1,
    ):
        self.num_workers = num_workers
        self._resources = dict(resources_per_worker)
        self._env = env
        self._max_concurrency = max_concurrency
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self.pg: PlacementGroup = placement_group(bundles, strategy=placement_strategy)
        if not self.pg.wait(120):
            remove_placement_group(self.pg)
            raise RuntimeError(
                f"could not reserve {num_workers} x {resources_per_worker} "
                f"(cluster resources: {ray_tpu.cluster_resources()})"
            )
        self.workers = [self._spawn(i) for i in range(num_workers)]
        ray_tpu.get([w.ping.remote() for w in self.workers])

    def _spawn(self, rank: int):
        return TrainWorker.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(self.pg, placement_group_bundle_index=rank),
            num_cpus=self._resources.get("CPU", 1),
            num_tpus=self._resources.get("TPU"),
            max_restarts=0,
            max_concurrency=self._max_concurrency,
        ).remote(rank, self.num_workers, self._env)

    def replace_worker(self, rank: int):
        """Elastic re-gang: a fresh actor on the dead rank's bundle; the
        surviving workers are untouched (train/elastic.py)."""
        try:
            ray_tpu.kill(self.workers[rank])
        except Exception:
            pass
        self.workers[rank] = self._spawn(rank)
        ray_tpu.get(self.workers[rank].ping.remote(), timeout=120)
        return self.workers[rank]

    def run_all(self, fn: Callable, config: Optional[Dict[str, Any]] = None) -> List[Any]:
        return [w.run.remote(fn, config) for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        remove_placement_group(self.pg)
