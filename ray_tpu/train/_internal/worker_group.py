"""Training worker group — the gang of host actors.

Equivalent of the reference's WorkerGroup
(reference: python/ray/train/_internal/worker_group.py:102). Each worker
is an actor pinned to a placement-group bundle; on TPU pods one worker
per host owns that host's chips.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    """Hosts one rank of the training gang."""

    def __init__(self, rank: int, world_size: int, env: Optional[Dict[str, str]] = None):
        self.rank = rank
        self.world_size = world_size
        for k, v in (env or {}).items():
            os.environ[k] = v

    def setup_session(self, result_queue, storage_dir: str, restore_checkpoint: Optional[str]):
        from ray_tpu.air.session import _Session, _set_session

        self._session = _Session(
            rank=self.rank,
            world_size=self.world_size,
            local_rank=self.rank,
            result_queue=result_queue,
            storage_dir=storage_dir,
            restore_checkpoint=restore_checkpoint,
        )
        _set_session(self._session)
        return True

    def run(self, fn: Callable, config: Optional[Dict[str, Any]] = None):
        from ray_tpu.air.session import _set_session

        _set_session(self._session)
        import inspect

        if config is not None or len(inspect.signature(fn).parameters) >= 1:
            return fn(config or {})
        return fn()

    def ping(self):
        return self.rank

    def node_id(self):
        return ray_tpu.get_runtime_context().node_id


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        env: Optional[Dict[str, str]] = None,
    ):
        self.num_workers = num_workers
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self.pg: PlacementGroup = placement_group(bundles, strategy=placement_strategy)
        if not self.pg.wait(120):
            remove_placement_group(self.pg)
            raise RuntimeError(
                f"could not reserve {num_workers} x {resources_per_worker} "
                f"(cluster resources: {ray_tpu.cluster_resources()})"
            )
        self.workers = [
            TrainWorker.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(self.pg, placement_group_bundle_index=i),
                num_cpus=resources_per_worker.get("CPU", 1),
                num_tpus=resources_per_worker.get("TPU"),
                max_restarts=0,
            ).remote(i, num_workers, env)
            for i in range(num_workers)
        ]
        ray_tpu.get([w.ping.remote() for w in self.workers])

    def run_all(self, fn: Callable, config: Optional[Dict[str, Any]] = None) -> List[Any]:
        return [w.run.remote(fn, config) for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        remove_placement_group(self.pg)
