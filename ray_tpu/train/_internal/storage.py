"""Checkpoint storage: orbax for sharded jax state + run directories.

Equivalent of the reference's StorageContext
(reference: python/ray/train/_internal/storage.py — 680 LoC pyarrow-fs
layer). Here local/NFS paths are handled directly and jax pytrees go
through orbax (which itself speaks tensorstore for sharded arrays on
real slices).

Commit protocol (round 9): every checkpoint directory is written with
tmp-dir → COMMIT-marker → atomic rename, so a writer killed at ANY
point can never corrupt the checkpoint that `latest_checkpoint()`
resolves to:

  1. payload is written into `<final>.tmp-<pid>-<nonce>` — a name
     `latest_checkpoint()` never considers
  2. a `COMMIT` marker (json: step/time/format) is written INSIDE the
     tmp dir, after the payload files are flushed
  3. the tmp dir is renamed to its final `checkpoint_XXXXXX` name —
     atomic on POSIX, so the final name appears with the marker already
     inside

`latest_checkpoint()` additionally requires the marker to be present
and parseable, which also screens out dirs produced by non-atomic
copies (cross-filesystem rsync, a partially copytree'd legacy dir).
"""
from __future__ import annotations

import contextlib
import json
import os
import time
import uuid
from typing import Any, Dict, Iterator, Optional

COMMIT_MARKER = "COMMIT"
_TMP_INFIX = ".tmp-"


def make_run_dir(storage_path: str, name: Optional[str]) -> str:
    run_name = name or f"run_{time.strftime('%Y%m%d-%H%M%S')}"
    path = os.path.join(os.path.expanduser(storage_path), run_name)
    os.makedirs(path, exist_ok=True)
    return path


# ------------------------------------------------------------------ commit
def commit_marker_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, COMMIT_MARKER)


def write_commit_marker(ckpt_dir: str, meta: Optional[Dict[str, Any]] = None) -> None:
    """Stamp `ckpt_dir` as committed. Written via its own tmp-file +
    rename so a torn marker write can never half-exist."""
    payload = dict(meta or {})
    payload.setdefault("time", time.time())
    tmp = os.path.join(ckpt_dir, f".{COMMIT_MARKER}.{uuid.uuid4().hex[:8]}")
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, commit_marker_path(ckpt_dir))


def is_committed(ckpt_dir: str) -> bool:
    """True iff `ckpt_dir` finished its atomic write: the final name
    (no tmp infix) AND a parseable COMMIT marker."""
    if _TMP_INFIX in os.path.basename(ckpt_dir):
        return False
    try:
        with open(commit_marker_path(ckpt_dir)) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def read_commit_meta(ckpt_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(commit_marker_path(ckpt_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@contextlib.contextmanager
def atomic_checkpoint_dir(final_dir: str, meta: Optional[Dict[str, Any]] = None) -> Iterator[str]:
    """Yield a tmp dir to write checkpoint payload into; on clean exit
    the marker is written and the dir atomically renamed to `final_dir`.
    A crash anywhere inside the block leaves only a `.tmp-` dir that
    `latest_checkpoint()` ignores and `sweep_stale_tmp_dirs()` reaps."""
    final_dir = os.path.abspath(final_dir)
    parent = os.path.dirname(final_dir)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{final_dir}{_TMP_INFIX}{os.getpid()}-{uuid.uuid4().hex[:8]}"
    os.makedirs(tmp)
    try:
        yield tmp
        write_commit_marker(tmp, meta)
        aside = None
        if os.path.isdir(final_dir):
            # re-save of the same step: move the old dir aside (tmp
            # name, so a crash leaves it reapable) IMMEDIATELY before
            # the rename-in, and reap it only after the new dir holds
            # the final name — the only window in which this step has
            # no committed dir is the two adjacent rename syscalls
            # (older committed checkpoints are untouched throughout)
            aside = f"{final_dir}{_TMP_INFIX}replaced-{uuid.uuid4().hex[:8]}"
            os.rename(final_dir, aside)
        os.rename(tmp, final_dir)
        if aside is not None:
            import shutil

            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise


def sweep_stale_tmp_dirs(run_dir: str) -> int:
    """Remove leftover `.tmp-` dirs from writers that died mid-save."""
    import shutil

    if not os.path.isdir(run_dir):
        return 0
    n = 0
    for d in os.listdir(run_dir):
        if d.startswith("checkpoint_") and _TMP_INFIX in d:
            shutil.rmtree(os.path.join(run_dir, d), ignore_errors=True)
            n += 1
    return n


# ------------------------------------------------------------------- orbax
def save_jax_state(path: str, state: Any) -> str:
    """Save a jax pytree (params/opt state) with orbax — atomically.
    orbax writes into a `.state.tmp-*` dir that is renamed to
    `<path>/state` only once fully flushed, and the dir-level COMMIT
    marker lands LAST — a process killed anywhere mid-save leaves
    `path` uncommitted, never half-written under its final name. The
    marker stays at the checkpoint-dir level (orbax owns the payload
    dir's contents and must not see foreign files)."""
    import shutil

    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    final = os.path.join(path, "state")
    tmp = os.path.join(path, f".state{_TMP_INFIX}{os.getpid()}-{uuid.uuid4().hex[:8]}")
    try:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(tmp, state, force=True)
        ckptr.wait_until_finished()
        aside = None
        if os.path.isdir(final):
            # old payload moves aside for only the instant between the
            # renames and is deleted after the new one holds the name
            aside = f"{tmp}-replaced"
            os.rename(final, aside)
        os.rename(tmp, final)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    write_commit_marker(path, {"format": "orbax-standard"})
    return path


def load_jax_state(path: str, target: Any) -> Any:
    """Restore into the structure/shardings of `target`."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(os.path.abspath(path), "state"), target)


# ------------------------------------------------------------ dir listing
def _checkpoint_dirs(run_dir: str):
    """All non-tmp checkpoint_* dirs, name-sorted (name order == step
    order for zero-padded names)."""
    if not os.path.isdir(run_dir):
        return []
    out = []
    for d in sorted(os.listdir(run_dir)):
        if not d.startswith("checkpoint_") or _TMP_INFIX in d:
            continue
        full = os.path.join(run_dir, d)
        if os.path.isdir(full):
            out.append(full)
    return out


def _committed_checkpoints(run_dir: str):
    return [d for d in _checkpoint_dirs(run_dir) if is_committed(d)]


def list_checkpoints(run_dir: str):
    """Resolvable checkpoints, oldest → newest. COMMITTED dirs when any
    exist; otherwise falls back to MARKER-LESS `checkpoint_*` dirs so a
    run dir written by a pre-commit-protocol release stays resumable
    after an upgrade. The fallback applies only when NO committed dir
    exists (once one new-protocol save lands, legacy dirs are never
    trusted over it), and a dir with a CORRUPT marker is excluded even
    from the fallback — a damaged marker means a new-protocol dir that
    was tampered with or half-copied, not a legacy write."""
    committed = _committed_checkpoints(run_dir)
    if committed:
        return committed
    return [
        d for d in _checkpoint_dirs(run_dir)
        if not os.path.exists(commit_marker_path(d))
    ]


def latest_checkpoint(run_dir: str) -> Optional[str]:
    """Newest resolvable checkpoint dir — uncommitted (killed mid-save),
    tmp, and corrupt-marker dirs are skipped, so a crash during a save
    always resolves to the previous good checkpoint. Marker-less legacy
    dirs are accepted only when no committed dir exists (see
    `list_checkpoints`)."""
    ckpts = list_checkpoints(run_dir)
    return ckpts[-1] if ckpts else None


def prune_checkpoints(run_dir: str, num_to_keep: Optional[int]):
    """Keep the newest `num_to_keep` RESOLVABLE checkpoints — the same
    set `latest_checkpoint()` chooses from (committed dirs, or the
    legacy marker-less fallback when none are committed — so legacy
    runs still age out). Corrupt-marker and `.tmp-` dirs never count
    against the budget and are never deleted here (the tmp sweep reaps
    `.tmp-` litter), and the newest resolvable checkpoint is never
    deleted — pruning can't take a committed dir in favor of an
    unreadable newer-named one."""
    if not num_to_keep:
        return
    import shutil

    for d in list_checkpoints(run_dir)[:-num_to_keep]:
        shutil.rmtree(d, ignore_errors=True)
