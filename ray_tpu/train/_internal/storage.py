"""Checkpoint storage: orbax for sharded jax state + run directories.

Equivalent of the reference's StorageContext
(reference: python/ray/train/_internal/storage.py — 680 LoC pyarrow-fs
layer). Here local/NFS paths are handled directly and jax pytrees go
through orbax (which itself speaks tensorstore for sharded arrays on
real slices).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional


def make_run_dir(storage_path: str, name: Optional[str]) -> str:
    run_name = name or f"run_{time.strftime('%Y%m%d-%H%M%S')}"
    path = os.path.join(os.path.expanduser(storage_path), run_name)
    os.makedirs(path, exist_ok=True)
    return path


def save_jax_state(path: str, state: Any) -> str:
    """Save a jax pytree (params/opt state) with orbax."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "state"), state, force=True)
    ckptr.wait_until_finished()
    return path


def load_jax_state(path: str, target: Any) -> Any:
    """Restore into the structure/shardings of `target`."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(os.path.abspath(path), "state"), target)


def latest_checkpoint(run_dir: str) -> Optional[str]:
    if not os.path.isdir(run_dir):
        return None
    ckpts = sorted(d for d in os.listdir(run_dir) if d.startswith("checkpoint_"))
    return os.path.join(run_dir, ckpts[-1]) if ckpts else None


def prune_checkpoints(run_dir: str, num_to_keep: Optional[int]):
    if not num_to_keep:
        return
    import shutil

    ckpts = sorted(d for d in os.listdir(run_dir) if d.startswith("checkpoint_"))
    for d in ckpts[:-num_to_keep]:
        shutil.rmtree(os.path.join(run_dir, d), ignore_errors=True)
