"""Orbax-backed pytree checkpointing for Train.

Equivalent of the reference's framework-native checkpoint formats
inside Train checkpoints (reference: train/_internal/storage.py ships
whatever the framework wrote into the checkpoint dir; torch uses
torch.save — the jax-native answer is orbax). These helpers write/read
a param/opt-state pytree inside a `ray_tpu.air.Checkpoint` directory,
so `train.report(..., checkpoint=...)` round-trips device arrays with
orbax's zarr sharded format instead of pickle:

    with_params = save_pytree_to_checkpoint(ckpt_dir, state.params)
    train.report(metrics, checkpoint=Checkpoint(ckpt_dir))
    # on restore:
    params = load_pytree_from_checkpoint(result.checkpoint.path)

Even this SYNC path writes atomically (tmp dir → rename → dir-level
COMMIT marker, train/_internal/storage.py): a process killed mid-save
can never corrupt the checkpoint that `storage.latest_checkpoint()`
resolves to. The async, never-block-the-step path is
`train/checkpoint_manager.py`.
"""
from __future__ import annotations

import os
import uuid
from typing import Any

from ray_tpu.train._internal.storage import _TMP_INFIX, write_commit_marker

_SUBDIR = "orbax_pytree"


def save_pytree_to_checkpoint(checkpoint_dir: str, pytree: Any) -> str:
    """Write `pytree` under the checkpoint dir with orbax; returns the
    orbax path. Atomic: orbax targets a tmp name, the final `_SUBDIR`
    name appears only via rename once the write fully flushed, and the
    checkpoint dir's COMMIT marker lands after that."""
    import shutil

    import orbax.checkpoint as ocp

    checkpoint_dir = os.path.abspath(checkpoint_dir)
    path = os.path.join(checkpoint_dir, _SUBDIR)
    tmp = os.path.join(
        checkpoint_dir, f".{_SUBDIR}{_TMP_INFIX}{os.getpid()}-{uuid.uuid4().hex[:8]}"
    )
    try:
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(tmp, pytree, force=True)
        getattr(ckptr, "wait_until_finished", lambda: None)()
        aside = None
        if os.path.isdir(path):
            # old payload moves aside for only the instant between the
            # renames and is deleted after the new one holds the name
            aside = f"{tmp}-replaced"
            os.rename(path, aside)
        os.rename(tmp, path)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    write_commit_marker(checkpoint_dir, {"format": "orbax-pytree"})
    return path


def load_pytree_from_checkpoint(checkpoint_dir: str, target: Any = None) -> Any:
    """Read the orbax pytree back (optionally restoring into `target`'s
    structure/shardings)."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(checkpoint_dir), _SUBDIR)
    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        return ckptr.restore(path, item=target)
    return ckptr.restore(path)
