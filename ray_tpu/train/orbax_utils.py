"""Orbax-backed pytree checkpointing for Train.

Equivalent of the reference's framework-native checkpoint formats
inside Train checkpoints (reference: train/_internal/storage.py ships
whatever the framework wrote into the checkpoint dir; torch uses
torch.save — the jax-native answer is orbax). These helpers write/read
a param/opt-state pytree inside a `ray_tpu.air.Checkpoint` directory,
so `train.report(..., checkpoint=...)` round-trips device arrays with
orbax's zarr sharded format instead of pickle:

    with_params = save_pytree_to_checkpoint(ckpt_dir, state.params)
    train.report(metrics, checkpoint=Checkpoint(ckpt_dir))
    # on restore:
    params = load_pytree_from_checkpoint(result.checkpoint.path)
"""
from __future__ import annotations

import os
from typing import Any

_SUBDIR = "orbax_pytree"


def save_pytree_to_checkpoint(checkpoint_dir: str, pytree: Any) -> str:
    """Write `pytree` under the checkpoint dir with orbax; returns the
    orbax path."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(checkpoint_dir), _SUBDIR)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, pytree, force=True)
    return path


def load_pytree_from_checkpoint(checkpoint_dir: str, target: Any = None) -> Any:
    """Read the orbax pytree back (optionally restoring into `target`'s
    structure/shardings)."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(checkpoint_dir), _SUBDIR)
    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        return ckptr.restore(path, item=target)
    return ckptr.restore(path)
