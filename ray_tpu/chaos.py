"""Shared deterministic fault injection — train slices AND serve replicas.

PR 5 built seeded, perfectly replayable preemption schedules for elastic
training; the serving plane needs the same property (a chaos run's
kill/hang/slow sequence must be a deterministic function of its seed, or
the regression tests and bench gates can't hold a number steady). This
module is the one home for both:

- STEP-keyed faults (``FaultEvent`` / ``PreemptionSchedule`` /
  ``PreemptionInjector``): the training side, injected into
  ``MultisliceTrainStep`` per (slice, step). ``train/fault_injection.py``
  re-exports these unchanged.
- TIME-keyed faults (``ChaosEvent`` / ``ChaosSchedule`` /
  ``ServeChaosInjector``): the serving side — events fire at seconds
  offsets from injector start against a live deployment's replica set.

Serve fault kinds, mirroring how replicas actually fail:

  kill      — SIGKILL the replica's worker process (spot reclaim, OOM
              kill). The hard case: no exception escapes, no K_FATAL is
              sent; detection is the GCS worker monitor + the
              controller's telemetry-staleness health check, and every
              in-flight request must be redispatched or failed typed.
  terminate — ``ray_tpu.kill`` (graceful-less actor destroy through the
              control plane): death is visible in the actor table
              immediately, exercising the fast-detection path.
  hang      — the replica process lives but stops responding: health
              pings stall, telemetry stops publishing, in-flight
              requests wedge. Detection must come from the BOUNDED
              ping/staleness path, and recovery from the controller
              declaring it dead and restarting it.
  slow      — a straggler: every request pays extra latency for the
              window, no membership change. Erodes deadlines without a
              recovery event (the deadline-shed path's workload).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

logger = logging.getLogger("ray_tpu.chaos")


# =====================================================================
# step-keyed training faults (moved verbatim from train/fault_injection)
# =====================================================================
class SlicePreempted(Exception):
    """A slice died (or was declared dead) mid-step."""

    def __init__(self, slice_idx: int, kind: str = "kill"):
        super().__init__(f"slice {slice_idx} preempted ({kind})")
        self.slice_idx = slice_idx
        self.kind = kind


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    step: int            # first step the fault is active
    slice_idx: int
    kind: str            # "kill" | "hang" | "slow"
    duration_steps: int = 3   # steps the slice stays down (kill/hang)
    notice_steps: int = 0     # advance maintenance notice before a kill
    slow_s: float = 0.0       # extra latency for "slow"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @property
    def end_step(self) -> int:
        return self.step + self.duration_steps


class PreemptionSchedule:
    """An ordered, replayable list of FaultEvents."""

    def __init__(self, events: Sequence[FaultEvent], seed: Optional[int] = None):
        self.events: List[FaultEvent] = sorted(
            events, key=lambda e: (e.step, e.slice_idx)
        )
        self.seed = seed

    @classmethod
    def generate(
        cls,
        seed: int,
        n_slices: int,
        total_steps: int,
        *,
        n_events: int = 2,
        kinds: Sequence[str] = ("kill", "hang", "slow"),
        min_gap_steps: int = 6,
        duration_steps: Tuple[int, int] = (2, 4),
        notice_prob: float = 0.5,
        notice_steps: int = 2,
        slow_s: float = 0.05,
    ) -> "PreemptionSchedule":
        """Deterministic in (seed, args): same inputs, same schedule.
        Events never target slice 0 (one survivor must always hold the
        authoritative state to broadcast from) and are spaced at least
        `min_gap_steps` apart so each outage resolves before the next."""
        import numpy as np

        if n_slices < 2:
            return cls([], seed=seed)
        rng = np.random.Generator(np.random.PCG64(seed))
        events: List[FaultEvent] = []
        step = int(rng.integers(min_gap_steps, max(min_gap_steps + 1, total_steps // 3)))
        for _ in range(n_events):
            if step >= total_steps - 1:
                break
            kind = str(rng.choice(list(kinds)))
            dur = int(rng.integers(duration_steps[0], duration_steps[1] + 1))
            notice = (
                notice_steps
                if kind == "kill" and rng.random() < notice_prob
                else 0
            )
            events.append(
                FaultEvent(
                    step=step,
                    slice_idx=int(rng.integers(1, n_slices)),
                    kind=kind,
                    duration_steps=dur if kind != "slow" else 0,
                    notice_steps=notice,
                    slow_s=slow_s if kind == "slow" else 0.0,
                )
            )
            step += dur + int(rng.integers(min_gap_steps, 2 * min_gap_steps))
        return cls(events, seed=seed)

    # ---------------------------------------------------------- replay io
    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "events": [e.to_dict() for e in self.events]}
        )

    @classmethod
    def from_json(cls, blob: str) -> "PreemptionSchedule":
        d = json.loads(blob)
        return cls([FaultEvent(**e) for e in d["events"]], seed=d.get("seed"))

    def __eq__(self, other) -> bool:
        return isinstance(other, PreemptionSchedule) and self.events == other.events

    def __repr__(self) -> str:
        return f"PreemptionSchedule(seed={self.seed}, events={self.events})"


class PreemptionInjector:
    """Drives a schedule against a MultisliceTrainStep.

    The trainer calls `check(slice_idx, step)` inside each slice's
    work, `maintenance_notice(step)` before dispatching a step, and
    `revivable(step)` when deciding whether to re-admit. `hang_s`
    bounds the simulated hang so test threads eventually unwind — it
    must exceed the trainer's probe timeout for the hang to be
    DETECTED as one."""

    def __init__(self, schedule: PreemptionSchedule, *, hang_s: float = 2.0):
        self.schedule = schedule
        self.hang_s = hang_s
        self.fired: List[FaultEvent] = []
        self._down: Dict[int, FaultEvent] = {}  # slice -> active outage

    # ---------------------------------------------------------- queries
    def maintenance_notice(self, step: int) -> List[FaultEvent]:
        """Kills whose advance-notice window covers `step` and have not
        fired yet — the signal for a priority checkpoint."""
        return [
            e
            for e in self.schedule.events
            if e.kind == "kill"
            and e.notice_steps > 0
            and e.step - e.notice_steps <= step < e.step
        ]

    def active_event(self, slice_idx: int, step: int) -> Optional[FaultEvent]:
        for e in self.schedule.events:
            if e.slice_idx != slice_idx:
                continue
            if e.kind == "slow" and e.step == step:
                return e
            if e.kind in ("kill", "hang") and e.step <= step < e.end_step:
                return e
        return None

    def revivable(self, step: int) -> Set[int]:
        """Slices whose outage has ended by `step` (ready to re-admit)."""
        out = set()
        for e in self.schedule.events:
            if e.kind in ("kill", "hang") and e.end_step <= step:
                out.add(e.slice_idx)
        # minus slices currently inside a LATER outage
        for e in self.schedule.events:
            if e.kind in ("kill", "hang") and e.step <= step < e.end_step:
                out.discard(e.slice_idx)
        return out

    # ------------------------------------------------------------ inject
    def check(self, slice_idx: int, step: int) -> None:
        """Called inside a slice's per-step work. Raises/sleeps per the
        schedule; a no-op for healthy (slice, step) pairs."""
        e = self.active_event(slice_idx, step)
        if e is None:
            return
        if e not in self.fired:
            self.fired.append(e)
        if e.kind == "kill":
            raise SlicePreempted(slice_idx, "kill")
        if e.kind == "hang":
            # wedge past the probe timeout, then die like the probe
            # would eventually observe — bounded so threads unwind
            time.sleep(self.hang_s)
            raise SlicePreempted(slice_idx, "hang")
        if e.kind == "slow":
            time.sleep(e.slow_s)


# =====================================================================
# time-keyed serve chaos
# =====================================================================
@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One serve fault: at `t_s` seconds after injector start, apply
    `kind` to a replica. `victim` pins the target by index into the
    sorted live membership at fire time; None lets the injector's
    seeded RNG pick (deterministic given the same membership)."""

    t_s: float
    kind: str                    # "kill" | "terminate" | "hang" | "slow"
    duration_s: float = 3.0      # hang/slow window
    slow_s: float = 0.2          # per-request latency for "slow"
    victim: Optional[int] = None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class ChaosSchedule:
    """Ordered, replayable serve-fault schedule (time-keyed twin of
    PreemptionSchedule — same json round-trip contract)."""

    KINDS = ("kill", "terminate", "hang", "slow")

    def __init__(self, events: Sequence[ChaosEvent], seed: Optional[int] = None):
        for e in events:
            if e.kind not in self.KINDS:
                raise ValueError(f"unknown chaos kind {e.kind!r} (valid: {self.KINDS})")
        self.events: List[ChaosEvent] = sorted(events, key=lambda e: e.t_s)
        self.seed = seed

    @classmethod
    def generate(
        cls,
        seed: int,
        window_s: float,
        *,
        n_events: int = 2,
        kinds: Sequence[str] = ("kill", "hang", "slow"),
        min_gap_s: float = 2.0,
        duration_s: Tuple[float, float] = (1.0, 3.0),
        slow_s: float = 0.2,
    ) -> "ChaosSchedule":
        """Deterministic in (seed, args). Events spread over the first
        `window_s` seconds with at least `min_gap_s` between them so one
        outage's recovery isn't hidden under the next fault."""
        import numpy as np

        rng = np.random.Generator(np.random.PCG64(seed))
        events: List[ChaosEvent] = []
        t = float(rng.uniform(min_gap_s, max(min_gap_s * 1.5, window_s / 3)))
        for _ in range(n_events):
            if t >= window_s:
                break
            kind = str(rng.choice(list(kinds)))
            dur = float(rng.uniform(*duration_s))
            events.append(ChaosEvent(
                t_s=round(t, 3), kind=kind,
                duration_s=round(dur, 3) if kind in ("hang", "slow") else 0.0,
                slow_s=slow_s if kind == "slow" else 0.0,
            ))
            t += dur + float(rng.uniform(min_gap_s, 2 * min_gap_s))
        return cls(events, seed=seed)

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "events": [e.to_dict() for e in self.events]}
        )

    @classmethod
    def from_json(cls, blob: str) -> "ChaosSchedule":
        d = json.loads(blob)
        return cls([ChaosEvent(**e) for e in d["events"]], seed=d.get("seed"))

    def __eq__(self, other) -> bool:
        return isinstance(other, ChaosSchedule) and self.events == other.events

    def __repr__(self) -> str:
        return f"ChaosSchedule(seed={self.seed}, events={self.events})"


class ServeChaosInjector:
    """Fires a ChaosSchedule at a live deployment's replicas.

    A driver-side harness tool (like ``loadgen.replica_metrics``): it
    reads membership through the controller per event — never on a
    request path — picks the victim deterministically from the seeded
    RNG over the SORTED live replica names, and applies the fault:

    - kill: SIGKILL the replica worker's OS pid (read from the replica's
      ``stats()``) — the replica gets no chance to say goodbye.
    - terminate: ``ray_tpu.kill`` on the actor handle.
    - hang / slow: arm the Replica wrapper's cooperative ``chaos()``
      wedge (health pings, stat reports and requests all stall for the
      window — what a stuck driver looks like from outside).

    ``fired`` records ``{"t_s", "kind", "replica", "pid"}`` per applied
    event (pid only for kills — the flight-recorder post-mortem key) for
    the loadgen report's chaos section.
    """

    def __init__(self, schedule: ChaosSchedule, app_name: str,
                 deployment_name: str):
        import random

        self.schedule = schedule
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.fired: List[Dict[str, Any]] = []
        self._rng = random.Random(schedule.seed or 0)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ driving
    def start(self) -> "ServeChaosInjector":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="serve-chaos"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _run(self) -> None:
        t0 = time.monotonic()
        for event in self.schedule.events:
            delay = event.t_s - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            try:
                self._fire(event)
            except Exception as e:  # a missed event must not kill the run
                logger.warning("chaos event %s failed: %s", event, e)
                self.fired.append({
                    "t_s": event.t_s, "kind": event.kind,
                    "replica": None, "error": str(e),
                })

    # ------------------------------------------------------------- firing
    def _members(self) -> List[str]:
        import ray_tpu
        from ray_tpu.serve.api import _get_controller

        info = ray_tpu.get(_get_controller().get_replicas_versioned.remote(
            self.app_name, self.deployment_name
        ))
        data = info["data"]
        names = data.get("replicas", []) if isinstance(data, dict) else (data or [])
        return sorted(names)

    def _fire(self, event: ChaosEvent) -> None:
        import signal

        import ray_tpu

        pid = None
        names = self._members()
        if not names:
            raise RuntimeError("no live replicas to target")
        idx = event.victim if event.victim is not None else \
            self._rng.randrange(len(names))
        name = names[idx % len(names)]
        actor = ray_tpu.get_actor(name)
        if event.kind == "kill":
            stats = ray_tpu.get(actor.stats.remote(), timeout=10)
            pid = stats.get("pid")
            if not pid:
                raise RuntimeError(f"replica {name} reports no pid")
            import os

            os.kill(int(pid), signal.SIGKILL)
        elif event.kind == "terminate":
            ray_tpu.kill(actor)
        elif event.kind in ("hang", "slow"):
            # fire-and-forget: a hang wedge by definition won't reply
            actor.chaos.remote(event.kind, event.duration_s, event.slow_s)
        else:  # pragma: no cover — schedule validation rejects these
            raise ValueError(f"unknown chaos kind {event.kind}")
        logger.info("chaos: %s replica %s (t=%.2fs)", event.kind, name, event.t_s)
        # the victim's pid rides the record: post-mortem assertions read
        # the SIGKILLed worker's flight-recorder ring by pid
        self.fired.append({"t_s": event.t_s, "kind": event.kind,
                           "replica": name, "pid": pid})
