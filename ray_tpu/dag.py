"""Lazy DAG API — `.bind()` builds a DAG of remote calls, `.execute()` runs it.

Equivalent of the reference's ray.dag
(reference: python/ray/dag/dag_node.py; compiled DAGs at
python/ray/dag/compiled_dag_node.py:141 are the reference's experimental
channel-based execution — here execution lowers onto the normal task
path; a compiled/fused path over device channels is the planned TPU
equivalent).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple


class DAGNode:
    def _resolve_args(self, args, kwargs):
        ra = [a.execute() if isinstance(a, DAGNode) else a for a in args]
        rk = {k: (v.execute() if isinstance(v, DAGNode) else v) for k, v in kwargs.items()}
        return ra, rk

    def execute(self):
        raise NotImplementedError


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        self._remote_fn = remote_fn
        self._args = args
        self._kwargs = kwargs

    def execute(self):
        args, kwargs = self._resolve_args(self._args, self._kwargs)
        return self._remote_fn.remote(*args, **kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, handle, method_name: str, args: Tuple, kwargs: Dict[str, Any]):
        self._handle = handle
        self._method = method_name
        self._args = args
        self._kwargs = kwargs

    def execute(self):
        args, kwargs = self._resolve_args(self._args, self._kwargs)
        return self._handle._invoke(self._method, args, kwargs, 1)


class InputNode(DAGNode):
    """Placeholder for runtime input (reference: dag/input_node.py)."""

    def __init__(self):
        self._value = None

    def execute(self):
        return self._value
