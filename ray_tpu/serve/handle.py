"""DeploymentHandle — client-side router.

Equivalent of the reference's handle + router
(reference: serve/handle.py DeploymentHandle; routing policy
serve/_private/replica_scheduler/pow_2_scheduler.py:44 — pick two random
replicas, send to the one with fewer outstanding requests; replica-set
freshness via long-poll, serve/_private/long_poll.py LongPollClient —
the controller pushes membership changes the moment they happen instead
of the handle polling or waiting for a routing failure).

Cache-affinity routing: when the deployment carries an
``affinity_config``, the membership push also builds a consistent-hash
ring (``vnodes`` virtual points per replica, hashed ONCE per refresh).
Each request then takes one digest of its prompt prefix (or explicit
``session_id``) and one bisect on the ring — repeat traffic lands on
the replica whose radix prefix cache is already hot, and a membership
change only remaps the keys that lived on the changed replicas. When
the preferred replica's outstanding count exceeds ``spill_threshold``
the request falls back to power-of-two least-loaded (affinity must not
amplify a hotspot); hits/spills/misses are counted per handle
(``routing_stats()``).

Zero-replica windows (scale-to-zero, a scale-down refresh mid-swap)
PARK the request: ``_reserve`` waits on the membership condition until
the next long-poll bump repopulates the replica set, bounded by
``no_replica_timeout_s`` with an actionable error. An empty set also
pings the controller (rate-limited) — the scale-from-zero demand
signal.

Failure semantics: every request gets a caller-generated request id
and an in-flight RECORD (method/args/replica/attempt count) held
handle-side. When the response resolves to a failure — whether it
arrived over the RPC path (``ActorDiedError`` from the sender loop) or
the direct transport (``ActorUnavailableError`` from the stream break)
— it funnels through ONE policy choke point, ``_on_failure``: requests
that were in flight on a replica that died are REQUEUED onto a
survivor when the deployment opted in (``fault_config={"redispatch":
True}``, safe for side-effect-free requests: result delivery is
end-of-request only, so nothing escaped the dead process) and
otherwise fail fast with a typed retryable ``ReplicaDiedError``;
shed/deadline failures propagate typed as-is. Requeue decisions use
only handle-local state — no controller round trips — and park under
the zero-replica machinery when no survivor exists yet.
"""
from __future__ import annotations

import bisect
import hashlib
import itertools
import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger("ray_tpu.serve")

# replica names whose get_actor already warned (module-wide: every
# handle refresh re-walks the same membership list)
_warned_replicas: set = set()

# caller-generated request ids: pid + a process-wide counter is unique
# and costs one integer increment on the submit path (uuid4 would pay
# an os.urandom read per request)
_rid_counter = itertools.count()


def _next_rid() -> str:
    return f"{os.getpid():x}-{next(_rid_counter):x}"


class DeploymentResponse:
    """Future-like response (reference: serve/handle.py DeploymentResponse).

    Failure handling: a resolved error runs through the owning handle's
    ``_on_failure`` choke point (when the response carries a request
    record), which either REQUEUES the request onto a surviving replica
    — the response then transparently re-awaits the new ref — or maps /
    re-raises the failure typed. Both transports' death signals land
    here: the RPC sender's ``ActorDiedError`` and the direct
    transport's stream-break ``ActorUnavailableError`` are delivered
    the same way (an error envelope on the result oid), so one loop
    covers both."""

    def __init__(self, ref, on_done=None, handle=None, record=None):
        self._ref = ref
        self._on_done = on_done
        self._handle = handle
        self._record = record
        self._settled = False

    def _settle(self):
        if not self._settled:
            self._settled = True
            if self._on_done:
                self._on_done()

    def _failed(self, e: BaseException):
        """Route a resolved failure through the handle's policy choke
        point. Returns True when the request was requeued (self._ref
        now points at the new attempt); raises the mapped typed error
        (or returns False to re-raise the original) otherwise."""
        if self._handle is None or self._record is None:
            return False
        new_ref = self._handle._on_failure(self._record, e)
        if new_ref is None:
            return False
        self._ref = new_ref
        return True

    def result(self, timeout: Optional[float] = None):
        try:
            while True:
                try:
                    return ray_tpu.get(self._ref, timeout=timeout)
                except Exception as e:
                    if not self._failed(e):
                        raise
        finally:
            self._settle()

    async def async_result(self, timeout: Optional[float] = 60.0):
        """Await the result natively (reference: the proxy awaits replica
        responses; a run_in_executor per request burned a pool thread at
        proxy QPS). Inline results resolve with zero thread hops; only
        blocking decode paths (shm/spill) use a worker thread."""
        from ray_tpu._private.worker import get_global_core

        import asyncio

        try:
            while True:
                try:
                    return await get_global_core().aget_value(self._ref, timeout)
                except Exception as e:
                    if self._handle is None or self._record is None:
                        raise
                    # _on_failure can PARK (zero survivors) — run it on
                    # a worker thread so a requeue during a replica
                    # restart never stalls the caller's event loop
                    new_ref = await asyncio.get_running_loop().run_in_executor(
                        None, self._handle._on_failure, self._record, e
                    )
                    if new_ref is None:
                        raise
                    self._ref = new_ref
        finally:
            self._settle()

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replica_names: List[str] = []
        self._replicas: List[Any] = []
        self._submits: List[Any] = []  # prebound direct-dispatch methods
        self._outstanding: Dict[str, int] = {}  # replica name -> in flight
        self._version = 0
        self._lock = threading.Lock()
        # membership condition: parked requests (zero-replica window)
        # wake on the long-poll bump that repopulates the replica set
        self._member_cv = threading.Condition(self._lock)
        self._method = "__call__"
        self._model_id = ""  # multiplexing: routes with model affinity
        self._poller: Optional[threading.Thread] = None
        self._closed = False
        # cache-affinity routing state (all rebuilt per membership push)
        self._affinity: Optional[Dict[str, Any]] = None
        self._ring_points: List[int] = []   # sorted vnode hash points
        self._ring_names: List[str] = []    # replica name per ring point
        self._name_to_idx: Dict[str, int] = {}
        # disaggregated pools: replica name -> role ("prefill"/"decode"),
        # per-role consistent-hash rings, and this handle's role filter
        # (options(pool=...); None = per-request resolution). The
        # cluster-inventory view resolves lazily (False = disabled).
        self._roles: Dict[str, str] = {}
        self._role_rings: Dict[str, Any] = {}
        self._pool: Optional[str] = None
        self._inv: Any = None
        self._astats = {"hits": 0, "spills": 0, "misses": 0, "inv_hits": 0}
        # failure-semantics state: the deployment's redispatch policy
        # (pushed with membership) + the failure/redispatch counters
        self._fault: Optional[Dict[str, Any]] = None
        self._fstats = {"redispatches": 0, "redispatch_failfast": 0,
                        "err_shed": 0, "err_replica_death": 0,
                        "err_deadline": 0, "err_other": 0}
        self._last_starve_ping = 0.0
        self.no_replica_timeout_s = float(
            os.environ.get("RAY_TPU_SERVE_NO_REPLICA_TIMEOUT_S", "30.0")
        )

    # -- replica set management ----------------------------------------
    def _apply_replicas(self, data, version: int):
        # payload forms: {"replicas": [...], "affinity": cfg|None} from
        # the controller, or a bare name list (legacy/tests — keeps the
        # current affinity config)
        if isinstance(data, dict):
            names = list(data.get("replicas") or ())
            affinity = data.get("affinity")
            fault = data.get("fault", self._fault)
            roles = dict(data.get("roles") or {})
        else:
            names = list(data or ())
            affinity = self._affinity
            fault = self._fault
            roles = self._roles
        handles, ok_names, submits = [], [], []
        for name in names:
            try:
                h = ray_tpu.get_actor(name)
            except Exception as e:
                # a replica the controller lists but we cannot resolve is
                # a routing hole — say so (once per name), don't bury it
                if name not in _warned_replicas:
                    _warned_replicas.add(name)
                    logger.warning(
                        "serve handle %s/%s: get_actor(%r) failed (%s); "
                        "routing around it", self.app_name,
                        self.deployment_name, name, e,
                    )
                continue
            handles.append(h)
            ok_names.append(name)
            # prebound shm-ring dispatch: binding .options(direct=True)
            # once per refresh keeps the per-request path allocation-free
            # (the fast path negotiates lazily per (caller, replica) and
            # falls back to RPC whenever the transport refuses)
            submits.append(h.handle_request.options(direct=True))
        # consistent-hash ring built ONCE per membership change: vnode
        # hashing happens here so the per-request affinity path is one
        # prefix digest + one bisect, nothing else
        ring: List[tuple] = []
        if affinity and ok_names:
            for name in ok_names:
                for v in range(affinity.get("vnodes", 32)):
                    point = int.from_bytes(
                        hashlib.md5(f"{name}#{v}".encode()).digest()[:8], "big"
                    )
                    ring.append((point, name))
            ring.sort()
        # pooled deployments route affinity WITHIN a role: each pool
        # gets its own ring (same vnode hashes, filtered), so a prefill
        # key never lands on a decode replica and vice versa
        role_rings: Dict[str, Any] = {}
        if ring and roles:
            for role in {roles[n] for n in ok_names if roles.get(n)}:
                sub = [(p, n) for p, n in ring if roles.get(n) == role]
                role_rings[role] = ([p for p, _ in sub], [n for _, n in sub])
        with self._member_cv:
            old = self._outstanding
            # parallel lists stay index-aligned even when some names
            # failed to resolve (names/handles previously diverged)
            self._replica_names = ok_names
            self._replicas = handles
            self._submits = submits
            # carry in-flight counts over for surviving replicas: a
            # zeroing refresh wiped the signal power-of-two routing
            # steers by, dogpiling the busiest replica after every
            # membership change
            self._outstanding = {n: old.get(n, 0) for n in ok_names}
            self._version = version
            self._affinity = affinity
            self._fault = fault
            self._ring_points = [p for p, _ in ring]
            self._ring_names = [n for _, n in ring]
            self._roles = roles
            self._role_rings = role_rings
            self._name_to_idx = {n: i for i, n in enumerate(ok_names)}
            # wake parked requests: the zero-replica window just closed
            if ok_names:
                self._member_cv.notify_all()

    def _refresh(self):
        from ray_tpu.serve.api import _get_controller

        controller = _get_controller()
        info = ray_tpu.get(
            controller.get_replicas_versioned.remote(self.app_name, self.deployment_name)
        )
        self._apply_replicas(info["data"], info["version"])
        self._ensure_poller()

    def _ensure_poller(self):
        if self._poller is not None and self._poller.is_alive():
            return
        self._poller = threading.Thread(target=self._poll_loop, daemon=True, name="serve-longpoll")
        self._poller.start()

    def _poll_loop(self):
        """Long-poll the controller: each request parks server-side until
        the replica set changes, so updates arrive push-fast with one
        outstanding RPC instead of periodic polling."""
        from ray_tpu.serve.api import _get_controller

        key = f"replicas::{self.app_name}::{self.deployment_name}"
        while not self._closed:
            try:
                controller = _get_controller()
                changed = ray_tpu.get(
                    controller.listen_for_change.remote({key: self._version}, timeout_s=20.0),
                    timeout=40.0,
                )
                if self._closed:
                    return
                if key in changed:
                    self._apply_replicas(changed[key]["data"], changed[key]["version"])
            except Exception:
                if self._closed:
                    return
                import time

                time.sleep(1.0)

    def options(self, method_name: str = "__call__", multiplexed_model_id: str = "",
                pool: Optional[str] = None, **_):
        h = DeploymentHandle(self.deployment_name, self.app_name)
        h._method = method_name
        h._model_id = multiplexed_model_id
        h._pool = pool if pool is not None else self._pool
        with self._lock:
            h._replica_names = list(self._replica_names)
            h._replicas = list(self._replicas)
            h._submits = list(self._submits)
            h._outstanding = dict(self._outstanding)
            h._version = self._version
            h._affinity = self._affinity
            h._fault = self._fault
            h._ring_points = list(self._ring_points)
            h._ring_names = list(self._ring_names)
            h._roles = dict(self._roles)
            h._role_rings = dict(self._role_rings)
            h._name_to_idx = dict(self._name_to_idx)
            h.no_replica_timeout_s = self.no_replica_timeout_s
        if h._replicas:
            # the snapshot needs its own long-poll subscription or it
            # would route to killed replicas after the next redeploy
            h._ensure_poller()
        return h

    # -- routing --------------------------------------------------------
    def _pick(self, eligible: Optional[List[int]] = None) -> int:
        """Power of two choices on outstanding counts
        (reference: pow_2_scheduler.py:44), optionally restricted to the
        `eligible` index subset (pool-role routing). With a multiplexed
        model id, the two candidates come from rendezvous hashing on the
        model id instead of randomness, so each model sticks to a stable
        pair of replicas and their multiplex LRUs keep hitting
        (reference: pow_2_scheduler's multiplexed-model-id
        preference)."""
        cands = eligible if eligible is not None \
            else list(range(len(self._replicas)))
        if len(cands) == 1:
            return cands[0]
        if self._model_id:
            import hashlib

            def score(i):
                h = hashlib.md5(f"{self._model_id}|{self._replica_names[i]}".encode())
                return h.digest()

            ranked = sorted(cands, key=score)
            a, b = ranked[0], ranked[1]
        else:
            a, b = random.sample(cands, 2)
        na, nb = self._replica_names[a], self._replica_names[b]
        return a if self._outstanding.get(na, 0) <= self._outstanding.get(nb, 0) else b

    def _affinity_digest(self, args: tuple) -> Optional[int]:
        """The ONE per-request hash of the affinity routing path: digest
        the request's session id (when present) or prompt prefix into a
        ring point. Returns None when affinity is off or the request has
        no routable key (counted as a miss by _reserve)."""
        cfg = self._affinity
        if not cfg:
            return None
        req = args[0] if args else None
        if self._method == "__serve_http_request__" and len(args) >= 3:
            req = args[2]  # ingress form: (http_method, subpath, body, query)
        mode = cfg.get("mode", "auto")
        key = None
        if isinstance(req, dict):
            sid = req.get("session_id")
            if sid is not None and mode in ("auto", "session"):
                key = str(sid).encode()
            else:
                req = req.get("prompt")
        if key is None and mode != "session":
            n = cfg.get("prefix_len", 32)
            if isinstance(req, str):
                key = req[:n].encode()
            elif isinstance(req, (list, tuple)) and req:
                key = b" ".join(str(t).encode() for t in req[:n])
        if key is None:
            return None
        return int.from_bytes(hashlib.md5(key).digest()[:8], "big")

    def _inventory(self):
        """Lazy cluster-inventory view (False = disabled): resolved once
        per handle, honoring the kill switch. Only pooled deployments
        pay the background refresh."""
        if self._inv is None:
            try:
                from ray_tpu.serve._internal import kv_plane

                self._inv = (kv_plane.InventoryView.instance()
                             if kv_plane.cluster_cache_enabled(None)
                             else False)
            except Exception:
                self._inv = False
        return self._inv or None

    def _route_affinity(self, akey: int, role: Optional[str] = None,
                        eligible: Optional[List[int]] = None):
        """Affinity lookup (lock held): returns (idx, kind) for the
        preferred replica, or (None, 'spills') when its outstanding
        count exceeds the spill threshold and least-loaded routing
        should take over. Per-request cost is one inventory dict probe
        (pooled deployments with the cluster cache on — the affinity
        digest IS the inventory key, so a prefix prefilled ANYWHERE
        routes its repeat traffic to the replica that owns it, ahead of
        the hash) plus one bisect on a ring hashed at membership-refresh
        time. Pooled deployments bisect their role's sub-ring."""
        spill_at = self._affinity.get("spill_threshold", 8)
        if self._roles and self._affinity.get("cluster", True):
            inv = self._inventory()
            owner = inv.owner_of(akey) if inv is not None else None
            if owner is not None:
                oidx = self._name_to_idx.get(owner)
                if (oidx is not None
                        and (eligible is None or oidx in eligible)
                        and self._outstanding.get(owner, 0) < spill_at):
                    return oidx, "inv_hits"
        points, names = self._ring_points, self._ring_names
        if role is not None and self._role_rings:
            sub = self._role_rings.get(role)
            if sub is not None and sub[0]:
                points, names = sub
        if not points:
            return None, "misses"
        i = bisect.bisect_left(points, akey)
        if i >= len(points):
            i = 0  # wrap: the ring is circular
        name = names[i]
        idx = self._name_to_idx.get(name)
        if idx is None:
            return None, "misses"
        if self._outstanding.get(name, 0) < spill_at:
            return idx, "hits"
        return None, "spills"

    def _park_for_members(self):
        """Wait (lock held, via the membership condition) for the
        zero-replica window to close: a scale-down refresh swap or a
        scale-from-zero. Bounded; the timeout error says what to check."""
        deadline = time.monotonic() + self.no_replica_timeout_s
        while not self._replicas:
            if self._closed:
                raise RuntimeError(
                    f"handle for {self.app_name}/{self.deployment_name} is closed"
                )
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"deployment {self.app_name}/{self.deployment_name} has "
                    f"had no replicas for {self.no_replica_timeout_s:.1f}s — "
                    f"scaled to zero without an autoscaler to wake it "
                    f"(set autoscaling_config min_replicas >= 1 or keep the "
                    f"control loop running), or a redeploy is stuck; "
                    f"serve.status() shows replica counts. Raise "
                    f"handle.no_replica_timeout_s to wait longer."
                )
            self._member_cv.wait(timeout=min(remaining, 1.0))
            if not self._replicas:
                # re-ping each wakeup tick (rate-limited inside): ONE
                # lost fire-and-forget starvation ping must not strand
                # a parked request on a controller that recovered —
                # outside the lock, the ping is an actor submit
                self._member_cv.release()
                try:
                    self._notify_starved()
                finally:
                    self._member_cv.acquire()

    def _notify_starved(self):
        """Rate-limited fire-and-forget demand signal to the controller:
        this handle is parking requests against an empty replica set."""
        now = time.monotonic()
        if now - self._last_starve_ping < 1.0:
            return
        self._last_starve_ping = now
        try:
            from ray_tpu.serve.api import _get_controller

            _get_controller().notify_starved.remote(
                self.app_name, self.deployment_name
            )
        except Exception:
            pass

    def _reserve(self, akey: Optional[int] = None,
                 role: Optional[str] = None):
        """Pick a replica and charge it one in-flight request — pick AND
        read under one lock (the long-poll thread can swap _replicas for
        a shorter list at any moment). An empty replica set PARKS the
        request on the membership condition instead of raising; affinity
        keys route via the consistent-hash ring with spill-to-
        least-loaded. With pool roles, candidates restrict to `role`'s
        pool — unless that pool is momentarily empty (replica death
        mid-restart), in which case any survivor serves: a paged engine
        imports/serves resumes regardless of role, so degrading beats
        parking. Returns (name, submit_method, route_kind) — route_kind
        is the affinity decision ("hits"/"spills"/"misses"/"inv_hits")
        or None without affinity, stamped on the request's lifeline."""
        with self._member_cv:
            if not self._replicas:
                self._park_for_members()
            eligible = None
            if role is not None and self._roles:
                eligible = [i for i, n in enumerate(self._replica_names)
                            if self._roles.get(n) == role]
                if not eligible:
                    eligible = None
            idx = None
            kind = None
            if self._affinity is not None:
                # keyless requests (no routable prompt/session) count as
                # misses too, so hits+spills+misses == affinity-routed
                # requests and the A/B counters don't understate traffic
                if akey is not None and (self._ring_points
                                         or self._role_rings):
                    idx, kind = self._route_affinity(akey, role, eligible)
                else:
                    kind = "misses"
                self._astats[kind] += 1
            if idx is None:
                idx = self._pick(eligible)
            name = self._replica_names[idx]
            self._outstanding[name] = self._outstanding.get(name, 0) + 1
            return name, self._submits[idx], kind

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        if not self._replicas:
            try:
                self._refresh()
            except Exception:
                pass  # controller briefly unreachable: _reserve parks
            if not self._replicas:
                self._notify_starved()
        if self._model_id:
            kwargs = {**kwargs, "_serve_multiplexed_model_id": self._model_id}
        # per-request failure record: the caller-generated request id,
        # the exact submit shape (so a redispatch resubmits verbatim),
        # and the attempt count — everything _on_failure needs, all
        # handle-local. The request BODY is never mutated (arbitrary
        # deployments echo it back) except for one normalization: a
        # relative `deadline_s` becomes the ABSOLUTE `deadline` here,
        # at first submit, so a redispatch cannot reset the clock. A
        # user-provided request_id becomes the record's id.
        rid = _next_rid()
        if args and isinstance(args[0], dict):
            req0 = args[0]
            # rid continuity: a user-provided request_id wins; a KV
            # resume body carries the ORIGINAL request's rid, and the
            # decode hop must ride the same lifeline instead of minting
            # a fresh id (one rid end-to-end across the migration)
            rid = req0.get("request_id") or req0.get("rid") or rid
            if req0.get("deadline_s") is not None:
                req0 = dict(req0)
                ds = req0.pop("deadline_s")
                if req0.get("deadline") is None:
                    req0["deadline"] = time.time() + float(ds)
                args = (req0,) + args[1:]
        record: Dict[str, Any] = {
            "rid": rid, "method": self._method, "args": args,
            "kwargs": kwargs, "replica": None, "attempts": 0,
        }

        def done():
            name = record.get("replica")
            with self._lock:
                # counts are name-keyed so a membership refresh neither
                # wipes them nor mis-charges a replica that took over
                # this index
                if name in self._outstanding:
                    self._outstanding[name] = max(0, self._outstanding[name] - 1)

        akey = self._affinity_digest(args) if self._affinity else None
        record["akey"] = akey
        # pooled deployments: an explicit options(pool=...) wins;
        # otherwise plain requests enter through the prefill pool and
        # KV-resume bodies (migrations) go straight to decode. The role
        # rides the record so a redispatch stays within the pool.
        role = self._pool
        if role is None and self._roles:
            req0 = args[0] if args else None
            role = "decode" if (isinstance(req0, dict)
                                and req0.get("__kv_resume__")) else "prefill"
        record["pool"] = role
        record["replica"], submit, route_kind = self._reserve(akey, role)
        try:
            # the prebound method rides the shm-ring direct transport
            # when negotiated, the RPC path otherwise — same call shape
            ref = submit.remote(self._method, args, kwargs)
        except Exception:
            done()
            self._refresh()
            record["replica"], submit, route_kind = self._reserve(akey, role)
            ref = submit.remote(self._method, args, kwargs)
        self._record_route(record, route_kind)
        return DeploymentResponse(ref, on_done=done, handle=self, record=record)

    def _record_route(self, record: Dict[str, Any],
                      route_kind: Optional[str]) -> None:
        """Drop the routing decision on the request's lifeline (caller
        process store + flight ring + span plane) — once per dispatch
        attempt, never on a reply path."""
        try:
            from ray_tpu.observability import lifeline
            from ray_tpu.util import tracing

            lifeline.record(
                record["rid"], "route", ctx=tracing.current_context(),
                app=self.app_name, deployment=self.deployment_name,
                replica=record.get("replica"),
                route=route_kind or "direct",
                pool=record.get("pool"),
                attempt=record.get("attempts", 0))
        except Exception:
            pass

    # -- failure policy -------------------------------------------------
    def _drop_replica(self, name: str) -> None:
        """Remove a replica observed dead from the local routing tables
        NOW — the controller's membership push confirms (and re-adds a
        restart) later, but until it lands neither pow-2 nor the
        affinity ring should keep steering requests at a corpse."""
        with self._lock:
            if name not in self._name_to_idx:
                return
            names = [n for n in self._replica_names if n != name]
            affinity, fault, version = self._affinity, self._fault, self._version
        self._apply_replicas(
            {"replicas": names, "affinity": affinity, "fault": fault}, version
        )

    def _on_failure(self, record: Dict[str, Any], exc: BaseException):
        """THE redispatch choke point. Every failed serve request —
        RPC-path actor death, direct-transport stream break, engine-side
        typed failure — funnels here from DeploymentResponse.

        Returns a NEW ref when the request was requeued onto a
        survivor; returns None to re-raise the original (already-typed)
        error; raises the mapped typed error otherwise. Decisions use
        handle-local state only: the error's class/flags, the pushed
        fault_config, and the record's attempt count. Requeue safety:
        replica death with ``started=False`` (or process death, where
        end-of-request delivery guarantees nothing escaped) is the ONLY
        redispatched shape — anything that may have produced observable
        output fails fast typed-retryable instead of silently running
        twice."""
        from ray_tpu.serve.errors import ReplicaDiedError, classify_error

        category, _retryable, _hint = classify_error(exc)
        dead_name = record.get("replica")
        with self._lock:
            self._fstats[f"err_{category.replace('-', '_')}"] += 1
            fault = self._fault or {}
            # the failed attempt's in-flight charge comes off now; a
            # requeue below re-charges the survivor via _reserve
            if dead_name in self._outstanding:
                self._outstanding[dead_name] = max(
                    0, self._outstanding[dead_name] - 1)
            record["replica"] = None
        if category != "replica-death":
            return None  # shed / deadline / other: propagate typed as-is
        if dead_name:
            self._drop_replica(dead_name)
        started = bool(getattr(exc, "started", False))
        allowed = fault.get("redispatch", False) and not started
        if not allowed or record["attempts"] >= fault.get("max_redispatches", 1):
            with self._lock:
                self._fstats["redispatch_failfast"] += 1
            if isinstance(exc, ReplicaDiedError):
                return None  # already the right type: re-raise original
            raise ReplicaDiedError(
                f"replica {dead_name or '?'} died with request "
                f"{record['rid']} in flight"
                + (" (redispatch disabled for this deployment)"
                   if not fault.get("redispatch", False) else
                   f" (after {record['attempts']} redispatch(es))"),
                started=started,
            ) from exc
        record["attempts"] += 1
        with self._lock:
            self._fstats["redispatches"] += 1
        logger.info(
            "serve %s/%s: redispatching request %s off dead replica %s "
            "(attempt %d)", self.app_name, self.deployment_name,
            record["rid"], dead_name, record["attempts"],
        )
        # _reserve parks under the zero-replica machinery when the dead
        # replica was the last one — the restart/scale-up push unparks
        record["replica"], submit, route_kind = self._reserve(
            record.get("akey"), record.get("pool"))
        try:
            from ray_tpu.observability import lifeline

            # the LOSER attempt is marked right on the timeline: which
            # replica died with the request in flight, and which
            # survivor the same rid was requeued onto
            lifeline.record(
                record["rid"], "redispatch",
                app=self.app_name, deployment=self.deployment_name,
                lost_replica=dead_name, replica=record["replica"],
                route=route_kind or "direct",
                attempt=record["attempts"])
        except Exception:
            pass
        return submit.remote(record["method"], record["args"], record["kwargs"])

    def routing_stats(self) -> Dict[str, Any]:
        """Affinity routing counters (transport_stats-style): hits =
        preferred replica taken, spills = preferred over the spill
        threshold so least-loaded took over, misses = affinity on but
        the request carried no routable key — plus the failure ledger
        (redispatches, fail-fasts, errors seen by taxonomy category)."""
        with self._lock:
            # ONE consistent copy under the lock, then derive from the
            # copy only: `total` computed from a second live read could
            # tear against a concurrent _reserve (hits+spills+misses
            # momentarily != routed)
            out = dict(self._astats)
            fstats = dict(self._fstats)
            out["affinity_enabled"] = self._affinity is not None
            out["ring_points"] = len(self._ring_points)
            out["replicas"] = len(self._replica_names)
            out["redispatch_enabled"] = bool(
                (self._fault or {}).get("redispatch"))
        out["total"] = (out["hits"] + out["spills"] + out["misses"]
                        + out["inv_hits"])
        out.update(fstats)
        return out

    def close(self):
        self._closed = True
        with self._member_cv:
            self._member_cv.notify_all()  # unpark waiters with the closed error
