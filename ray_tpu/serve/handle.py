"""DeploymentHandle — client-side router.

Equivalent of the reference's handle + router
(reference: serve/handle.py DeploymentHandle; routing policy
serve/_private/replica_scheduler/pow_2_scheduler.py:44 — pick two random
replicas, send to the one with fewer outstanding requests; replica-set
freshness via long-poll, serve/_private/long_poll.py LongPollClient —
the controller pushes membership changes the moment they happen instead
of the handle polling or waiting for a routing failure).

Cache-affinity routing: when the deployment carries an
``affinity_config``, the membership push also builds a consistent-hash
ring (``vnodes`` virtual points per replica, hashed ONCE per refresh).
Each request then takes one digest of its prompt prefix (or explicit
``session_id``) and one bisect on the ring — repeat traffic lands on
the replica whose radix prefix cache is already hot, and a membership
change only remaps the keys that lived on the changed replicas. When
the preferred replica's outstanding count exceeds ``spill_threshold``
the request falls back to power-of-two least-loaded (affinity must not
amplify a hotspot); hits/spills/misses are counted per handle
(``routing_stats()``).

Zero-replica windows (scale-to-zero, a scale-down refresh mid-swap)
PARK the request: ``_reserve`` waits on the membership condition until
the next long-poll bump repopulates the replica set, bounded by
``no_replica_timeout_s`` with an actionable error. An empty set also
pings the controller (rate-limited) — the scale-from-zero demand
signal.
"""
from __future__ import annotations

import bisect
import hashlib
import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger("ray_tpu.serve")

# replica names whose get_actor already warned (module-wide: every
# handle refresh re-walks the same membership list)
_warned_replicas: set = set()


class DeploymentResponse:
    """Future-like response (reference: serve/handle.py DeploymentResponse)."""

    def __init__(self, ref, on_done=None):
        self._ref = ref
        self._on_done = on_done

    def result(self, timeout: Optional[float] = None):
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        finally:
            if self._on_done:
                self._on_done()

    async def async_result(self, timeout: Optional[float] = 60.0):
        """Await the result natively (reference: the proxy awaits replica
        responses; a run_in_executor per request burned a pool thread at
        proxy QPS). Inline results resolve with zero thread hops; only
        blocking decode paths (shm/spill) use a worker thread."""
        from ray_tpu._private.worker import get_global_core

        try:
            return await get_global_core().aget_value(self._ref, timeout)
        finally:
            if self._on_done:
                self._on_done()

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replica_names: List[str] = []
        self._replicas: List[Any] = []
        self._submits: List[Any] = []  # prebound direct-dispatch methods
        self._outstanding: Dict[str, int] = {}  # replica name -> in flight
        self._version = 0
        self._lock = threading.Lock()
        # membership condition: parked requests (zero-replica window)
        # wake on the long-poll bump that repopulates the replica set
        self._member_cv = threading.Condition(self._lock)
        self._method = "__call__"
        self._model_id = ""  # multiplexing: routes with model affinity
        self._poller: Optional[threading.Thread] = None
        self._closed = False
        # cache-affinity routing state (all rebuilt per membership push)
        self._affinity: Optional[Dict[str, Any]] = None
        self._ring_points: List[int] = []   # sorted vnode hash points
        self._ring_names: List[str] = []    # replica name per ring point
        self._name_to_idx: Dict[str, int] = {}
        self._astats = {"hits": 0, "spills": 0, "misses": 0}
        self._last_starve_ping = 0.0
        self.no_replica_timeout_s = float(
            os.environ.get("RAY_TPU_SERVE_NO_REPLICA_TIMEOUT_S", "30.0")
        )

    # -- replica set management ----------------------------------------
    def _apply_replicas(self, data, version: int):
        # payload forms: {"replicas": [...], "affinity": cfg|None} from
        # the controller, or a bare name list (legacy/tests — keeps the
        # current affinity config)
        if isinstance(data, dict):
            names = list(data.get("replicas") or ())
            affinity = data.get("affinity")
        else:
            names = list(data or ())
            affinity = self._affinity
        handles, ok_names, submits = [], [], []
        for name in names:
            try:
                h = ray_tpu.get_actor(name)
            except Exception as e:
                # a replica the controller lists but we cannot resolve is
                # a routing hole — say so (once per name), don't bury it
                if name not in _warned_replicas:
                    _warned_replicas.add(name)
                    logger.warning(
                        "serve handle %s/%s: get_actor(%r) failed (%s); "
                        "routing around it", self.app_name,
                        self.deployment_name, name, e,
                    )
                continue
            handles.append(h)
            ok_names.append(name)
            # prebound shm-ring dispatch: binding .options(direct=True)
            # once per refresh keeps the per-request path allocation-free
            # (the fast path negotiates lazily per (caller, replica) and
            # falls back to RPC whenever the transport refuses)
            submits.append(h.handle_request.options(direct=True))
        # consistent-hash ring built ONCE per membership change: vnode
        # hashing happens here so the per-request affinity path is one
        # prefix digest + one bisect, nothing else
        ring: List[tuple] = []
        if affinity and ok_names:
            for name in ok_names:
                for v in range(affinity.get("vnodes", 32)):
                    point = int.from_bytes(
                        hashlib.md5(f"{name}#{v}".encode()).digest()[:8], "big"
                    )
                    ring.append((point, name))
            ring.sort()
        with self._member_cv:
            old = self._outstanding
            # parallel lists stay index-aligned even when some names
            # failed to resolve (names/handles previously diverged)
            self._replica_names = ok_names
            self._replicas = handles
            self._submits = submits
            # carry in-flight counts over for surviving replicas: a
            # zeroing refresh wiped the signal power-of-two routing
            # steers by, dogpiling the busiest replica after every
            # membership change
            self._outstanding = {n: old.get(n, 0) for n in ok_names}
            self._version = version
            self._affinity = affinity
            self._ring_points = [p for p, _ in ring]
            self._ring_names = [n for _, n in ring]
            self._name_to_idx = {n: i for i, n in enumerate(ok_names)}
            # wake parked requests: the zero-replica window just closed
            if ok_names:
                self._member_cv.notify_all()

    def _refresh(self):
        from ray_tpu.serve.api import _get_controller

        controller = _get_controller()
        info = ray_tpu.get(
            controller.get_replicas_versioned.remote(self.app_name, self.deployment_name)
        )
        self._apply_replicas(info["data"], info["version"])
        self._ensure_poller()

    def _ensure_poller(self):
        if self._poller is not None and self._poller.is_alive():
            return
        self._poller = threading.Thread(target=self._poll_loop, daemon=True, name="serve-longpoll")
        self._poller.start()

    def _poll_loop(self):
        """Long-poll the controller: each request parks server-side until
        the replica set changes, so updates arrive push-fast with one
        outstanding RPC instead of periodic polling."""
        from ray_tpu.serve.api import _get_controller

        key = f"replicas::{self.app_name}::{self.deployment_name}"
        while not self._closed:
            try:
                controller = _get_controller()
                changed = ray_tpu.get(
                    controller.listen_for_change.remote({key: self._version}, timeout_s=20.0),
                    timeout=40.0,
                )
                if self._closed:
                    return
                if key in changed:
                    self._apply_replicas(changed[key]["data"], changed[key]["version"])
            except Exception:
                if self._closed:
                    return
                import time

                time.sleep(1.0)

    def options(self, method_name: str = "__call__", multiplexed_model_id: str = "", **_):
        h = DeploymentHandle(self.deployment_name, self.app_name)
        h._method = method_name
        h._model_id = multiplexed_model_id
        with self._lock:
            h._replica_names = list(self._replica_names)
            h._replicas = list(self._replicas)
            h._submits = list(self._submits)
            h._outstanding = dict(self._outstanding)
            h._version = self._version
            h._affinity = self._affinity
            h._ring_points = list(self._ring_points)
            h._ring_names = list(self._ring_names)
            h._name_to_idx = dict(self._name_to_idx)
            h.no_replica_timeout_s = self.no_replica_timeout_s
        if h._replicas:
            # the snapshot needs its own long-poll subscription or it
            # would route to killed replicas after the next redeploy
            h._ensure_poller()
        return h

    # -- routing --------------------------------------------------------
    def _pick(self) -> int:
        """Power of two choices on outstanding counts
        (reference: pow_2_scheduler.py:44). With a multiplexed model id,
        the two candidates come from rendezvous hashing on the model id
        instead of randomness, so each model sticks to a stable pair of
        replicas and their multiplex LRUs keep hitting (reference:
        pow_2_scheduler's multiplexed-model-id preference)."""
        n = len(self._replicas)
        if n == 1:
            return 0
        if self._model_id:
            import hashlib

            def score(i):
                h = hashlib.md5(f"{self._model_id}|{self._replica_names[i]}".encode())
                return h.digest()

            ranked = sorted(range(n), key=score)
            a, b = ranked[0], ranked[1]
        else:
            a, b = random.sample(range(n), 2)
        na, nb = self._replica_names[a], self._replica_names[b]
        return a if self._outstanding.get(na, 0) <= self._outstanding.get(nb, 0) else b

    def _affinity_digest(self, args: tuple) -> Optional[int]:
        """The ONE per-request hash of the affinity routing path: digest
        the request's session id (when present) or prompt prefix into a
        ring point. Returns None when affinity is off or the request has
        no routable key (counted as a miss by _reserve)."""
        cfg = self._affinity
        if not cfg:
            return None
        req = args[0] if args else None
        if self._method == "__serve_http_request__" and len(args) >= 3:
            req = args[2]  # ingress form: (http_method, subpath, body, query)
        mode = cfg.get("mode", "auto")
        key = None
        if isinstance(req, dict):
            sid = req.get("session_id")
            if sid is not None and mode in ("auto", "session"):
                key = str(sid).encode()
            else:
                req = req.get("prompt")
        if key is None and mode != "session":
            n = cfg.get("prefix_len", 32)
            if isinstance(req, str):
                key = req[:n].encode()
            elif isinstance(req, (list, tuple)) and req:
                key = b" ".join(str(t).encode() for t in req[:n])
        if key is None:
            return None
        return int.from_bytes(hashlib.md5(key).digest()[:8], "big")

    def _route_affinity(self, akey: int):
        """Ring lookup (lock held): returns (idx, 'hits') for the
        preferred replica, or (None, 'spills') when its outstanding
        count exceeds the spill threshold and least-loaded routing
        should take over. Per-request cost is one bisect — the ring was
        hashed at membership-refresh time."""
        i = bisect.bisect_left(self._ring_points, akey)
        if i >= len(self._ring_points):
            i = 0  # wrap: the ring is circular
        name = self._ring_names[i]
        idx = self._name_to_idx.get(name)
        if idx is None:
            return None, "misses"
        spill_at = self._affinity.get("spill_threshold", 8)
        if self._outstanding.get(name, 0) < spill_at:
            return idx, "hits"
        return None, "spills"

    def _park_for_members(self):
        """Wait (lock held, via the membership condition) for the
        zero-replica window to close: a scale-down refresh swap or a
        scale-from-zero. Bounded; the timeout error says what to check."""
        deadline = time.monotonic() + self.no_replica_timeout_s
        while not self._replicas:
            if self._closed:
                raise RuntimeError(
                    f"handle for {self.app_name}/{self.deployment_name} is closed"
                )
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"deployment {self.app_name}/{self.deployment_name} has "
                    f"had no replicas for {self.no_replica_timeout_s:.1f}s — "
                    f"scaled to zero without an autoscaler to wake it "
                    f"(set autoscaling_config min_replicas >= 1 or keep the "
                    f"control loop running), or a redeploy is stuck; "
                    f"serve.status() shows replica counts. Raise "
                    f"handle.no_replica_timeout_s to wait longer."
                )
            self._member_cv.wait(timeout=min(remaining, 1.0))
            if not self._replicas:
                # re-ping each wakeup tick (rate-limited inside): ONE
                # lost fire-and-forget starvation ping must not strand
                # a parked request on a controller that recovered —
                # outside the lock, the ping is an actor submit
                self._member_cv.release()
                try:
                    self._notify_starved()
                finally:
                    self._member_cv.acquire()

    def _notify_starved(self):
        """Rate-limited fire-and-forget demand signal to the controller:
        this handle is parking requests against an empty replica set."""
        now = time.monotonic()
        if now - self._last_starve_ping < 1.0:
            return
        self._last_starve_ping = now
        try:
            from ray_tpu.serve.api import _get_controller

            _get_controller().notify_starved.remote(
                self.app_name, self.deployment_name
            )
        except Exception:
            pass

    def _reserve(self, akey: Optional[int] = None):
        """Pick a replica and charge it one in-flight request — pick AND
        read under one lock (the long-poll thread can swap _replicas for
        a shorter list at any moment). An empty replica set PARKS the
        request on the membership condition instead of raising; affinity
        keys route via the consistent-hash ring with spill-to-
        least-loaded. Returns (name, submit_method)."""
        with self._member_cv:
            if not self._replicas:
                self._park_for_members()
            idx = None
            if self._affinity is not None:
                # keyless requests (no routable prompt/session) count as
                # misses too, so hits+spills+misses == affinity-routed
                # requests and the A/B counters don't understate traffic
                if akey is not None and self._ring_points:
                    idx, kind = self._route_affinity(akey)
                else:
                    kind = "misses"
                self._astats[kind] += 1
            if idx is None:
                idx = self._pick()
            name = self._replica_names[idx]
            self._outstanding[name] = self._outstanding.get(name, 0) + 1
            return name, self._submits[idx]

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        if not self._replicas:
            try:
                self._refresh()
            except Exception:
                pass  # controller briefly unreachable: _reserve parks
            if not self._replicas:
                self._notify_starved()
        picked: Dict[str, str] = {}

        def done():
            name = picked.get("name")
            with self._lock:
                # counts are name-keyed so a membership refresh neither
                # wipes them nor mis-charges a replica that took over
                # this index
                if name in self._outstanding:
                    self._outstanding[name] = max(0, self._outstanding[name] - 1)

        if self._model_id:
            kwargs = {**kwargs, "_serve_multiplexed_model_id": self._model_id}
        akey = self._affinity_digest(args) if self._affinity else None
        picked["name"], submit = self._reserve(akey)
        try:
            # the prebound method rides the shm-ring direct transport
            # when negotiated, the RPC path otherwise — same call shape
            ref = submit.remote(self._method, args, kwargs)
        except Exception:
            done()
            self._refresh()
            picked["name"], submit = self._reserve(akey)
            ref = submit.remote(self._method, args, kwargs)
        return DeploymentResponse(ref, on_done=done)

    def routing_stats(self) -> Dict[str, Any]:
        """Affinity routing counters (transport_stats-style): hits =
        preferred replica taken, spills = preferred over the spill
        threshold so least-loaded took over, misses = affinity on but
        the request carried no routable key."""
        with self._lock:
            out = dict(self._astats)
            out["total"] = sum(self._astats.values())
            out["affinity_enabled"] = self._affinity is not None
            out["ring_points"] = len(self._ring_points)
            out["replicas"] = len(self._replica_names)
            return out

    def close(self):
        self._closed = True
        with self._member_cv:
            self._member_cv.notify_all()  # unpark waiters with the closed error
