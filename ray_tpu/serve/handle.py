"""DeploymentHandle — client-side router.

Equivalent of the reference's handle + router
(reference: serve/handle.py DeploymentHandle; routing policy
serve/_private/replica_scheduler/pow_2_scheduler.py:44 — pick two random
replicas, send to the one with fewer outstanding requests; replica-set
freshness via long-poll, serve/_private/long_poll.py LongPollClient —
the controller pushes membership changes the moment they happen instead
of the handle polling or waiting for a routing failure).
"""
from __future__ import annotations

import logging
import random
import threading
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger("ray_tpu.serve")

# replica names whose get_actor already warned (module-wide: every
# handle refresh re-walks the same membership list)
_warned_replicas: set = set()


class DeploymentResponse:
    """Future-like response (reference: serve/handle.py DeploymentResponse)."""

    def __init__(self, ref, on_done=None):
        self._ref = ref
        self._on_done = on_done

    def result(self, timeout: Optional[float] = None):
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        finally:
            if self._on_done:
                self._on_done()

    async def async_result(self, timeout: Optional[float] = 60.0):
        """Await the result natively (reference: the proxy awaits replica
        responses; a run_in_executor per request burned a pool thread at
        proxy QPS). Inline results resolve with zero thread hops; only
        blocking decode paths (shm/spill) use a worker thread."""
        from ray_tpu._private.worker import get_global_core

        try:
            return await get_global_core().aget_value(self._ref, timeout)
        finally:
            if self._on_done:
                self._on_done()

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replica_names: List[str] = []
        self._replicas: List[Any] = []
        self._submits: List[Any] = []  # prebound direct-dispatch methods
        self._outstanding: Dict[str, int] = {}  # replica name -> in flight
        self._version = 0
        self._lock = threading.Lock()
        self._method = "__call__"
        self._model_id = ""  # multiplexing: routes with model affinity
        self._poller: Optional[threading.Thread] = None
        self._closed = False

    # -- replica set management ----------------------------------------
    def _apply_replicas(self, names: List[str], version: int):
        handles, ok_names, submits = [], [], []
        for name in names:
            try:
                h = ray_tpu.get_actor(name)
            except Exception as e:
                # a replica the controller lists but we cannot resolve is
                # a routing hole — say so (once per name), don't bury it
                if name not in _warned_replicas:
                    _warned_replicas.add(name)
                    logger.warning(
                        "serve handle %s/%s: get_actor(%r) failed (%s); "
                        "routing around it", self.app_name,
                        self.deployment_name, name, e,
                    )
                continue
            handles.append(h)
            ok_names.append(name)
            # prebound shm-ring dispatch: binding .options(direct=True)
            # once per refresh keeps the per-request path allocation-free
            # (the fast path negotiates lazily per (caller, replica) and
            # falls back to RPC whenever the transport refuses)
            submits.append(h.handle_request.options(direct=True))
        with self._lock:
            old = self._outstanding
            # parallel lists stay index-aligned even when some names
            # failed to resolve (names/handles previously diverged)
            self._replica_names = ok_names
            self._replicas = handles
            self._submits = submits
            # carry in-flight counts over for surviving replicas: a
            # zeroing refresh wiped the signal power-of-two routing
            # steers by, dogpiling the busiest replica after every
            # membership change
            self._outstanding = {n: old.get(n, 0) for n in ok_names}
            self._version = version

    def _refresh(self):
        from ray_tpu.serve.api import _get_controller

        controller = _get_controller()
        info = ray_tpu.get(
            controller.get_replicas_versioned.remote(self.app_name, self.deployment_name)
        )
        self._apply_replicas(info["data"], info["version"])
        self._ensure_poller()

    def _ensure_poller(self):
        if self._poller is not None and self._poller.is_alive():
            return
        self._poller = threading.Thread(target=self._poll_loop, daemon=True, name="serve-longpoll")
        self._poller.start()

    def _poll_loop(self):
        """Long-poll the controller: each request parks server-side until
        the replica set changes, so updates arrive push-fast with one
        outstanding RPC instead of periodic polling."""
        from ray_tpu.serve.api import _get_controller

        key = f"replicas::{self.app_name}::{self.deployment_name}"
        while not self._closed:
            try:
                controller = _get_controller()
                changed = ray_tpu.get(
                    controller.listen_for_change.remote({key: self._version}, timeout_s=20.0),
                    timeout=40.0,
                )
                if self._closed:
                    return
                if key in changed:
                    self._apply_replicas(changed[key]["data"], changed[key]["version"])
            except Exception:
                if self._closed:
                    return
                import time

                time.sleep(1.0)

    def options(self, method_name: str = "__call__", multiplexed_model_id: str = "", **_):
        h = DeploymentHandle(self.deployment_name, self.app_name)
        h._method = method_name
        h._model_id = multiplexed_model_id
        with self._lock:
            h._replica_names = list(self._replica_names)
            h._replicas = list(self._replicas)
            h._submits = list(self._submits)
            h._outstanding = dict(self._outstanding)
            h._version = self._version
        if h._replicas:
            # the snapshot needs its own long-poll subscription or it
            # would route to killed replicas after the next redeploy
            h._ensure_poller()
        return h

    # -- routing --------------------------------------------------------
    def _pick(self) -> int:
        """Power of two choices on outstanding counts
        (reference: pow_2_scheduler.py:44). With a multiplexed model id,
        the two candidates come from rendezvous hashing on the model id
        instead of randomness, so each model sticks to a stable pair of
        replicas and their multiplex LRUs keep hitting (reference:
        pow_2_scheduler's multiplexed-model-id preference)."""
        n = len(self._replicas)
        if n == 1:
            return 0
        if self._model_id:
            import hashlib

            def score(i):
                h = hashlib.md5(f"{self._model_id}|{self._replica_names[i]}".encode())
                return h.digest()

            ranked = sorted(range(n), key=score)
            a, b = ranked[0], ranked[1]
        else:
            a, b = random.sample(range(n), 2)
        na, nb = self._replica_names[a], self._replica_names[b]
        return a if self._outstanding.get(na, 0) <= self._outstanding.get(nb, 0) else b

    def _reserve(self):
        """Pick a replica and charge it one in-flight request — pick AND
        read under one lock (the long-poll thread can swap _replicas for
        a shorter list at any moment). Returns (name, submit_method)."""
        with self._lock:
            if not self._replicas:
                raise RuntimeError(f"no replicas for {self.deployment_name}")
            idx = self._pick()
            name = self._replica_names[idx]
            self._outstanding[name] = self._outstanding.get(name, 0) + 1
            return name, self._submits[idx]

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        if not self._replicas:
            self._refresh()
        picked: Dict[str, str] = {}

        def done():
            name = picked.get("name")
            with self._lock:
                # counts are name-keyed so a membership refresh neither
                # wipes them nor mis-charges a replica that took over
                # this index
                if name in self._outstanding:
                    self._outstanding[name] = max(0, self._outstanding[name] - 1)

        if self._model_id:
            kwargs = {**kwargs, "_serve_multiplexed_model_id": self._model_id}
        picked["name"], submit = self._reserve()
        try:
            # the prebound method rides the shm-ring direct transport
            # when negotiated, the RPC path otherwise — same call shape
            ref = submit.remote(self._method, args, kwargs)
        except Exception:
            done()
            self._refresh()
            picked["name"], submit = self._reserve()
            ref = submit.remote(self._method, args, kwargs)
        return DeploymentResponse(ref, on_done=done)

    def close(self):
        self._closed = True
