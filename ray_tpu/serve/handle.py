"""DeploymentHandle — client-side router.

Equivalent of the reference's handle + router
(reference: serve/handle.py DeploymentHandle; routing policy
serve/_private/replica_scheduler/pow_2_scheduler.py:44 — pick two random
replicas, send to the one with fewer outstanding requests).
"""
from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like response (reference: serve/handle.py DeploymentResponse)."""

    def __init__(self, ref, on_done=None):
        self._ref = ref
        self._on_done = on_done

    def result(self, timeout: Optional[float] = None):
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        finally:
            if self._on_done:
                self._on_done()

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replicas: List[Any] = []
        self._outstanding: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._method = "__call__"

    # -- replica set management ----------------------------------------
    def _refresh(self):
        from ray_tpu.serve.api import _get_controller

        controller = _get_controller()
        infos = ray_tpu.get(controller.get_replicas.remote(self.app_name, self.deployment_name))
        with self._lock:
            self._replicas = [ray_tpu.get_actor(name) for name in infos]
            self._outstanding = {i: 0 for i in range(len(self._replicas))}

    def options(self, method_name: str = "__call__", **_):
        h = DeploymentHandle(self.deployment_name, self.app_name)
        h._method = method_name
        with self._lock:
            h._replicas = list(self._replicas)
            h._outstanding = dict(self._outstanding)
        return h

    # -- routing --------------------------------------------------------
    def _pick(self) -> int:
        """Power of two choices on outstanding counts."""
        n = len(self._replicas)
        if n == 1:
            return 0
        a, b = random.sample(range(n), 2)
        return a if self._outstanding.get(a, 0) <= self._outstanding.get(b, 0) else b

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        if not self._replicas:
            self._refresh()
        if not self._replicas:
            raise RuntimeError(f"no replicas for {self.deployment_name}")
        with self._lock:
            idx = self._pick()
            self._outstanding[idx] = self._outstanding.get(idx, 0) + 1
        replica = self._replicas[idx]

        def done():
            with self._lock:
                self._outstanding[idx] = max(0, self._outstanding.get(idx, 1) - 1)

        try:
            ref = replica.handle_request.remote(self._method, args, kwargs)
        except Exception:
            done()
            self._refresh()
            replica = self._replicas[self._pick()]
            ref = replica.handle_request.remote(self._method, args, kwargs)
        return DeploymentResponse(ref, on_done=done)
