"""ServeController — the reconciling control loop.

Equivalent of the reference's ServeController + DeploymentState
(reference: serve/_private/controller.py:91, deployment_state.py —
declarative target state → replica actors started/stopped to match).

Async actor: config consumers (handles, proxies) subscribe via
LONG-POLL (`listen_for_change`, reference: serve/_private/long_poll.py
LongPollHost) — a request parks on a version mismatch and returns the
moment the controller bumps it, so replica-set updates push rather than
poll. A background control loop autoscales deployments on queue depth
(reference: serve/_private/autoscaling_policy.py — scale toward
total_ongoing_requests / target_ongoing_requests, clamped to
[min_replicas, max_replicas]).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER"


@ray_tpu.remote(max_concurrency=16)
class Replica:
    """Wraps one instance of the user's deployment class
    (reference: serve/_private/replica.py)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        import inspect
        import threading

        def _resolve(v):
            # handle markers from deployment graphs → live handles
            if isinstance(v, dict) and "__serve_handle__" in v:
                from ray_tpu.serve.handle import DeploymentHandle

                app_name, dep_name = v["__serve_handle__"]
                h = DeploymentHandle(dep_name, app_name)
                h._refresh()
                return h
            return v

        init_args = tuple(_resolve(a) for a in init_args)
        init_kwargs = {k: _resolve(v) for k, v in init_kwargs.items()}
        if inspect.isclass(cls_or_fn):
            self.instance = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.instance = cls_or_fn
        self.num_requests = 0
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()

    def handle_request(self, method: str, args, kwargs):
        with self._ongoing_lock:
            self.num_requests += 1
            self._ongoing += 1
        model_id = kwargs.pop("_serve_multiplexed_model_id", "")
        token = None
        if model_id:
            from ray_tpu.serve.multiplex import _set_model_id

            token = _set_model_id(model_id)
        try:
            fn = self.instance if method == "__call__" else getattr(self.instance, method)
            result = fn(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)
            return result
        finally:
            if token is not None:
                from ray_tpu.serve.multiplex import _current_model_id

                _current_model_id.reset(token)
            with self._ongoing_lock:
                self._ongoing -= 1

    def health(self):
        return True

    def stats(self):
        return {"num_requests": self.num_requests, "ongoing": self._ongoing}


@ray_tpu.remote
class ServeControllerActor:
    def __init__(self):
        from ray_tpu.serve.deployment_scheduler import DeploymentScheduler

        # app -> deployment -> record
        self.apps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.routes: Dict[str, tuple] = {}  # route_prefix -> (app, deployment)
        self._counter = 0
        self._scheduler = DeploymentScheduler()
        # node-grouped order the last upgrade drained in (introspection)
        self._last_drain_order: List[List[str]] = []
        # long-poll state: key -> monotonically increasing version; parked
        # listeners wake on bump (reference: LongPollHost notify_changed)
        self._versions: Dict[str, int] = {}
        self._events: Dict[str, Any] = {}
        self._loop_started = False

    # ------------------------------------------------------------ long poll
    def _bump(self, key: str):
        import asyncio

        self._versions[key] = self._versions.get(key, 0) + 1
        ev = self._events.get(key)
        if ev is not None:
            ev.set()
            self._events[key] = asyncio.Event()

    def _event_for(self, key: str):
        import asyncio

        ev = self._events.get(key)
        if ev is None:
            ev = self._events[key] = asyncio.Event()
        return ev

    async def listen_for_change(self, snapshot: Dict[str, int], timeout_s: float = 30.0):
        """Park until any key's version moves past the caller's snapshot;
        returns {key: {"version": v, "data": payload}} for changed keys
        (empty dict on timeout — caller re-issues)."""
        import asyncio

        deadline = time.monotonic() + timeout_s
        while True:
            changed = {
                key: {"version": self._versions.get(key, 0), "data": self._payload(key)}
                for key, ver in snapshot.items()
                if self._versions.get(key, 0) != ver
            }
            if changed:
                return changed
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {}
            waiters = [asyncio.ensure_future(self._event_for(key).wait()) for key in snapshot]
            done, pending = await asyncio.wait(
                waiters, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
            )
            for p in pending:
                p.cancel()
            if not done:
                return {}

    def _payload(self, key: str):
        if key == "routes":
            return dict(self.routes)
        if key.startswith("replicas::"):
            _, app, dep = key.split("::", 2)
            return self.apps.get(app, {}).get(dep, {}).get("replicas", [])
        return None

    # ------------------------------------------------------------ deploy
    async def deploy(
        self,
        app_name: str,
        deployment_name: str,
        cls_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        num_replicas: int,
        route_prefix: Optional[str],
        ray_actor_options: Optional[dict] = None,
        autoscaling_config: Optional[dict] = None,
        is_ingress: bool = False,
    ):
        import cloudpickle

        cls = cloudpickle.loads(cls_blob)
        app = self.apps.setdefault(app_name, {})
        old = app.get(deployment_name)
        rec = {
            "cls": cls,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "replicas": [],
            "num_replicas": num_replicas,
            "route_prefix": route_prefix,
            "ray_actor_options": dict(ray_actor_options or {}),
            "autoscaling": autoscaling_config,
            "is_ingress": is_ingress,
            "deploy_time": time.time(),
        }
        if autoscaling_config:
            rec["num_replicas"] = autoscaling_config.get(
                "initial_replicas", autoscaling_config.get("min_replicas", 1)
            )
        # stage new replicas BEFORE committing the record: a failed deploy
        # (e.g. __init__ raises) must leave the previous version serving
        import asyncio

        self._scale_to(app_name, deployment_name, rec["num_replicas"], rec=rec)
        try:
            await asyncio.gather(
                *(ray_tpu.get_actor(name).health.remote() for name in rec["replicas"])
            )
        except Exception:
            for name in rec["replicas"]:
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:
                    pass
            raise
        app[deployment_name] = rec
        if old:
            # versioned in-place upgrade: the new replica set is healthy
            # and published FIRST (long-poll bump below swaps handles and
            # proxies over), then old replicas DRAIN their in-flight
            # requests before dying — a config redeploy must not drop
            # requests (reference: serve rolling updates +
            # graceful_shutdown_wait_loop_s)
            doomed = [n for n in old["replicas"] if n not in rec["replicas"]]
            # node-by-node rolling drain: one node's old replicas finish
            # their in-flight requests and die before the next node's are
            # touched (reference: serve drain-aware rolling updates)
            groups = self._scheduler.drain_groups(doomed)
            self._last_drain_order = groups

            async def _drain_by_node():
                for grp in groups:
                    await asyncio.gather(*(self._drain_and_kill(n) for n in grp))

            asyncio.ensure_future(_drain_by_node())
        if route_prefix:
            self.routes[route_prefix] = (app_name, deployment_name, is_ingress)
            self._bump("routes")
        self._bump(f"replicas::{app_name}::{deployment_name}")
        return True

    def _scale_to(self, app_name: str, deployment_name: str, target: int, rec=None):
        import asyncio

        rec = rec if rec is not None else self.apps[app_name][deployment_name]
        cur = list(rec["replicas"])
        while len(cur) < target:
            self._counter += 1
            name = f"SERVE_REPLICA::{app_name}::{deployment_name}::{self._counter}"
            # placement policy: spread by default, pack TPU replicas
            # (reference: serve/_private/deployment_scheduler.py)
            opts = self._scheduler.place(name, rec["ray_actor_options"])
            Replica.options(name=name, max_concurrency=16, **opts).remote(
                rec["cls"], rec["init_args"], rec["init_kwargs"]
            )
            cur.append(name)
        while len(cur) > target:
            name = cur.pop()
            # drain before killing: the replica may still be serving
            # accepted requests (reference: graceful_shutdown_wait_loop_s)
            asyncio.ensure_future(self._drain_and_kill(name))
        rec["replicas"] = cur
        rec["num_replicas"] = target

    async def _drain_and_kill(self, name: str, timeout_s: float = 15.0):
        import asyncio

        deadline = time.monotonic() + timeout_s
        try:
            h = ray_tpu.get_actor(name)
            while time.monotonic() < deadline:
                stats = await h.stats.remote()
                if stats["ongoing"] == 0:
                    break
                await asyncio.sleep(0.25)
        except Exception:
            pass
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except Exception:
            pass
        self._scheduler.forget(name)

    # ------------------------------------------------------ autoscale loop
    async def run_control_loop(self, period_s: float = 1.0):
        """Queue-depth autoscaling (fire-and-forget from serve.run)."""
        import asyncio

        if self._loop_started:
            return
        self._loop_started = True
        while True:
            await asyncio.sleep(period_s)
            for app_name, deps in list(self.apps.items()):
                for dep_name, rec in list(deps.items()):
                    cfg = rec.get("autoscaling")
                    if not cfg:
                        continue
                    try:
                        await self._autoscale_one(app_name, dep_name, rec, cfg)
                    except Exception:
                        import logging

                        logging.getLogger("ray_tpu.serve").warning(
                            "autoscale cycle failed for %s::%s", app_name, dep_name, exc_info=True
                        )

    async def _autoscale_one(self, app_name, dep_name, rec, cfg):
        import asyncio

        stats = await asyncio.gather(
            *(ray_tpu.get_actor(n).stats.remote() for n in rec["replicas"])
        )
        ongoing = sum(s["ongoing"] for s in stats)
        target_per = max(1e-6, cfg.get("target_ongoing_requests", 2))
        desired = int(ongoing / target_per + 0.999)
        desired = max(cfg.get("min_replicas", 1), min(cfg.get("max_replicas", 8), desired))
        if desired != len(rec["replicas"]):
            self._scale_to(app_name, dep_name, desired)
            self._bump(f"replicas::{app_name}::{dep_name}")

    # ------------------------------------------------------------- queries
    async def get_replicas_versioned(self, app_name: str, deployment_name: str):
        key = f"replicas::{app_name}::{deployment_name}"
        return {"version": self._versions.get(key, 0), "data": self._payload(key)}

    async def get_routes(self) -> Dict[str, tuple]:
        return dict(self.routes)

    async def last_drain_order(self) -> List[List[str]]:
        """Node-grouped replica names the last upgrade drained in order."""
        return self._last_drain_order

    async def replica_placements(self) -> Dict[str, str]:
        """replica name -> node id chosen by the deployment scheduler."""
        return dict(self._scheduler._placed)

    async def delete_app(self, app_name: str):
        app = self.apps.pop(app_name, None)
        if not app:
            return False
        for dep_name, dep in app.items():
            for name in dep["replicas"]:
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:
                    pass
            if dep.get("route_prefix"):
                self.routes.pop(dep["route_prefix"], None)
            self._bump(f"replicas::{app_name}::{dep_name}")
        self._bump("routes")
        return True

    async def status(self) -> Dict[str, Any]:
        out = {}
        for app_name, deps in self.apps.items():
            out[app_name] = {
                name: {
                    "num_replicas": len(d["replicas"]),
                    "route_prefix": d["route_prefix"],
                    "autoscaling": bool(d.get("autoscaling")),
                }
                for name, d in deps.items()
            }
        return out
