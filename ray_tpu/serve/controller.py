"""ServeController — the reconciling control loop.

Equivalent of the reference's ServeController + DeploymentState
(reference: serve/_private/controller.py:91, deployment_state.py —
declarative target state → replica actors started/stopped to match).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER"


@ray_tpu.remote(max_concurrency=16)
class Replica:
    """Wraps one instance of the user's deployment class
    (reference: serve/_private/replica.py)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        import inspect

        if inspect.isclass(cls_or_fn):
            self.instance = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.instance = cls_or_fn
        self.num_requests = 0

    def handle_request(self, method: str, args, kwargs):
        self.num_requests += 1
        fn = self.instance if method == "__call__" else getattr(self.instance, method)
        result = fn(*args, **kwargs)
        import inspect

        if inspect.iscoroutine(result):
            import asyncio

            result = asyncio.run(result)
        return result

    def health(self):
        return True

    def stats(self):
        return {"num_requests": self.num_requests}


@ray_tpu.remote
class ServeControllerActor:
    def __init__(self):
        # app -> deployment -> record
        self.apps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.routes: Dict[str, tuple] = {}  # route_prefix -> (app, deployment)
        self._counter = 0

    def deploy(
        self,
        app_name: str,
        deployment_name: str,
        cls_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        num_replicas: int,
        route_prefix: Optional[str],
        ray_actor_options: Optional[dict] = None,
    ):
        import cloudpickle

        cls = cloudpickle.loads(cls_blob)
        app = self.apps.setdefault(app_name, {})
        old = app.get(deployment_name)
        if old:
            for name in old["replicas"]:
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:
                    pass
        replicas = []
        opts = dict(ray_actor_options or {})
        for i in range(num_replicas):
            self._counter += 1
            name = f"SERVE_REPLICA::{app_name}::{deployment_name}::{self._counter}"
            Replica.options(name=name, max_concurrency=16, **opts).remote(cls, init_args, init_kwargs)
            replicas.append(name)
        # wait for readiness
        for name in replicas:
            h = ray_tpu.get_actor(name)
            ray_tpu.get(h.health.remote())
        app[deployment_name] = {
            "replicas": replicas,
            "num_replicas": num_replicas,
            "route_prefix": route_prefix,
            "deploy_time": time.time(),
        }
        if route_prefix:
            self.routes[route_prefix] = (app_name, deployment_name)
        return True

    def get_replicas(self, app_name: str, deployment_name: str) -> List[str]:
        return self.apps.get(app_name, {}).get(deployment_name, {}).get("replicas", [])

    def get_routes(self) -> Dict[str, tuple]:
        return dict(self.routes)

    def delete_app(self, app_name: str):
        app = self.apps.pop(app_name, None)
        if not app:
            return False
        for dep in app.values():
            for name in dep["replicas"]:
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:
                    pass
            if dep.get("route_prefix"):
                self.routes.pop(dep["route_prefix"], None)
        return True

    def status(self) -> Dict[str, Any]:
        out = {}
        for app_name, deps in self.apps.items():
            out[app_name] = {
                name: {"num_replicas": d["num_replicas"], "route_prefix": d["route_prefix"]}
                for name, d in deps.items()
            }
        return out
