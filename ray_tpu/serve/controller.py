"""ServeController — the reconciling control loop.

Equivalent of the reference's ServeController + DeploymentState
(reference: serve/_private/controller.py:91, deployment_state.py —
declarative target state → replica actors started/stopped to match).

Async actor: config consumers (handles, proxies) subscribe via
LONG-POLL (`listen_for_change`, reference: serve/_private/long_poll.py
LongPollHost) — a request parks on a version mismatch and returns the
moment the controller bumps it, so replica-set updates push rather than
poll. A background control loop autoscales deployments on queue depth
(reference: serve/_private/autoscaling_policy.py — scale toward
total_ongoing_requests / target_ongoing_requests, clamped to
[min_replicas, max_replicas]).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER"

# replica stats older than this are treated as missing by the autoscaler
# (a hung replica must not pin the load average with its last snapshot)
STATS_STALE_S = 5.0
# how recently a handle must have reported starvation (zero replicas,
# parked requests) for the autoscaler to scale a 0-replica deployment up
STARVED_WINDOW_S = 5.0


def _health_knobs() -> Dict[str, float]:
    """Replica health-check / restart knobs, env-overridable (read at
    controller construction so a test's environment reaches the actor).

    health_stale_s: telemetry silence that makes a replica a SUSPECT
        (replicas publish every 0.5–2s; suspects get pinged, nothing
        else does — steady state stays RPC-free).
    ping_timeout_s: bounded health-ping wait; a suspect that can't
        answer within it is declared wedged and replaced.
    startup_grace_s: staleness is not judged until a replica has either
        published once or been alive this long — a replica loading a
        model / compiling its programs must not be "wedged" at birth
        (the PR-5 compile-grace lesson, serve-side).
    restart_backoff_s / crash window/threshold / cooldown: see
        serve/_internal/lifecycle.CrashLoopBreaker.
    """
    import os

    e = os.environ.get
    return {
        "health_stale_s": float(e("RAY_TPU_SERVE_HEALTH_STALE_S", "5.0")),
        "ping_timeout_s": float(e("RAY_TPU_SERVE_PING_TIMEOUT_S", "2.0")),
        "startup_grace_s": float(e("RAY_TPU_SERVE_STARTUP_GRACE_S", "120.0")),
        "restart_backoff_s": float(e("RAY_TPU_SERVE_RESTART_BACKOFF_S", "0.5")),
        "crash_loop_window_s": float(e("RAY_TPU_SERVE_CRASH_LOOP_WINDOW_S", "30.0")),
        "crash_loop_threshold": int(e("RAY_TPU_SERVE_CRASH_LOOP_THRESHOLD", "5")),
        "breaker_cooldown_s": float(e("RAY_TPU_SERVE_BREAKER_COOLDOWN_S", "30.0")),
    }


def _fetch_replica_stats() -> Dict[str, Dict[str, Any]]:
    """Merged per-replica load stats from the GCS `serve` telemetry
    table — the same last-write-wins-per-reporter snapshots `/api/serve`
    serves (each Replica publishes `replica:<name>` entries from its own
    process). ONE GCS round trip (observability.fetch_snapshots) covers
    every replica of every deployment; the autoscaler never calls into a
    replica synchronously.
    """
    from ray_tpu.observability import fetch_snapshots

    out: Dict[str, Dict[str, Any]] = {}
    engines: Dict[str, Dict[str, Any]] = {}
    for snap in fetch_snapshots("serve", timeout=2.0).values():
        if not isinstance(snap, dict):
            continue
        for key, val in snap.items():
            if not (isinstance(key, str) and isinstance(val, dict)):
                continue
            if key.startswith("replica:"):
                out[key[len("replica:"):]] = val
            elif key.startswith("engine:"):
                # engine metric snapshots ride along for the SLO
                # evaluator (joined to replicas by pid: engine name is
                # `llm-<pid>`, replica payloads carry "pid") — stashed
                # under a reserved key so replica-name lookups
                # (`SERVE_REPLICA::...`) can never collide
                engines[key[len("engine:"):]] = val
    out["__engines__"] = engines
    return out


def _fetch_actor_states() -> Dict[str, str]:
    """Replica-actor name -> GCS actor state, ONE state-table RPC for
    every replica of every deployment (the health loop's fast death
    signal: a SIGKILLed worker's actor flips DEAD the moment the raylet
    reports the process gone — no staleness window to wait out)."""
    try:
        from ray_tpu.util.state import list_actors

        return {
            a["name"]: a.get("state", "")
            for a in list_actors()
            if isinstance(a.get("name"), str)
            and a["name"].startswith("SERVE_REPLICA::")
        }
    except Exception:
        return {}


def _prune_replica_telemetry(name: str) -> None:
    """Drop a dead replica's `replica:<name>` snapshot from the GCS
    serve telemetry table (best-effort; blocking — callers run it off
    the control loop)."""
    try:
        from ray_tpu.observability import prune_snapshot_key

        prune_snapshot_key("serve", f"replica:{name}")
    except Exception:
        pass


@ray_tpu.remote(max_concurrency=16)
class Replica:
    """Wraps one instance of the user's deployment class
    (reference: serve/_private/replica.py)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, replica_name=None):
        import inspect
        import os
        import threading

        # worker pid: the chaos harness SIGKILLs it; surfaced in stats()
        # and the telemetry payload
        self._pid = os.getpid()
        # cooperative fault injection (ray_tpu.chaos): a "hang" wedge
        # stalls health pings, stat publishing AND requests until the
        # deadline — what a stuck driver looks like from outside; "slow"
        # taxes each request with extra latency
        self._wedged_until = 0.0
        self._slow_until = 0.0
        self._slow_s = 0.0

        def _resolve(v):
            # handle markers from deployment graphs → live handles
            if isinstance(v, dict) and "__serve_handle__" in v:
                from ray_tpu.serve.handle import DeploymentHandle

                app_name, dep_name = v["__serve_handle__"]
                h = DeploymentHandle(dep_name, app_name)
                h._refresh()
                return h
            return v

        init_args = tuple(_resolve(a) for a in init_args)
        init_kwargs = {k: _resolve(v) for k, v in init_kwargs.items()}
        if replica_name:
            # record the actor name for the KV plane BEFORE the instance
            # constructs: the deployment reads its own (app, deployment,
            # replica) coordinates back from kv_plane to build pool
            # handles without threading them through user init kwargs
            try:
                from ray_tpu.serve._internal import kv_plane

                kv_plane.set_replica_name(replica_name)
            except Exception:
                pass
        if inspect.isclass(cls_or_fn):
            self.instance = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.instance = cls_or_fn
        self.num_requests = 0
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()
        self._name = replica_name
        if replica_name:
            # stat reporter: queue depth + in-flight counts ride the
            # PR-4 telemetry path into the GCS `serve` table (and thus
            # /api/serve), where the controller's autoscaler reads them
            # — no synchronous controller→replica stat RPCs
            t = threading.Thread(
                target=self._report_loop, daemon=True, name="serve-replica-stats"
            )
            t.start()

    def _instance_load(self) -> float:
        """Deployment-reported load (e.g. the LLM engine's queued +
        resident request count via `__serve_load__`), 0 when the
        deployment doesn't expose one."""
        fn = getattr(self.instance, "__serve_load__", None)
        if fn is None:
            return 0.0
        try:
            return float(fn())
        except Exception:
            return 0.0

    def _load(self) -> float:
        """The autoscaling load signal. Deployments that track their own
        request lifecycle (async engines completing requests after
        handle_request returns) report through `__serve_load__` and that
        number IS the load — summing it with `_ongoing` would double-
        count the blocking-path requests that appear in both."""
        inst = self._instance_load()
        return inst if inst > 0 else float(self._ongoing)

    def _report_loop(self, period_s: float = 0.5, idle_period_s: float = 2.0):
        from ray_tpu import observability

        key = f"replica:{self._name}"
        last_sig = None
        while True:
            period = period_s
            try:
                if time.time() < self._wedged_until:
                    # chaos wedge: a stuck process publishes nothing —
                    # the controller must notice via staleness + ping
                    time.sleep(0.1)
                    continue
                payload = {
                    "t": time.time(),
                    "load": self._load(),
                    "ongoing": self._ongoing,
                    "queued": self._instance_load(),
                    "num_requests": self.num_requests,
                    "pid": self._pid,
                }
                # KV-plane duck-typed extras: pool role + per-pool
                # autoscaling signals, and the block-inventory digests
                # other replicas' InventoryViews resolve owners from
                fn = getattr(self.instance, "__serve_pool_signals__", None)
                if fn is not None:
                    try:
                        psig = fn()
                    except Exception:
                        psig = None
                    if isinstance(psig, dict):
                        payload["pool_signals"] = psig
                        if psig.get("pool"):
                            payload["pool"] = psig["pool"]
                fn = getattr(self.instance, "__serve_kv_inventory__", None)
                if fn is not None:
                    try:
                        inv = fn()
                        if inv:
                            payload["kv_inventory"] = list(inv)
                    except Exception:
                        pass
                # idle backoff: an unchanged zero-load signal still
                # publishes (the autoscaler treats >5s-stale stats as
                # missing, which would BLOCK downscale-to-min) but at a
                # quarter of the active rate — R idle replicas stop
                # costing 2R GCS pushes/s
                sig = (payload["load"], payload["queued"], payload["num_requests"])
                if sig == last_sig and payload["load"] == 0:
                    period = idle_period_s
                last_sig = sig
                observability.publish_snapshot("serve", {key: payload})
            except Exception:
                pass
            time.sleep(period)

    def handle_request(self, method: str, args, kwargs):
        now = time.time()
        if now < self._wedged_until:
            # wedged: requests stall exactly like the rest of the
            # process (the controller's kill-and-restart breaks them
            # out, exercising the redispatch path)
            while time.time() < self._wedged_until:
                time.sleep(0.05)
        elif now < self._slow_until and self._slow_s > 0:
            time.sleep(self._slow_s)
        with self._ongoing_lock:
            self.num_requests += 1
            self._ongoing += 1
        model_id = kwargs.pop("_serve_multiplexed_model_id", "")
        token = None
        if model_id:
            from ray_tpu.serve.multiplex import _set_model_id

            token = _set_model_id(model_id)
        try:
            fn = self.instance if method == "__call__" else getattr(self.instance, method)
            result = fn(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)
            return result
        finally:
            if token is not None:
                from ray_tpu.serve.multiplex import _current_model_id

                _current_model_id.reset(token)
            with self._ongoing_lock:
                self._ongoing -= 1

    def health(self):
        # a wedged replica cannot answer its health ping — that is the
        # point: the controller's bounded wait times out and declares it
        while time.time() < self._wedged_until:
            time.sleep(0.05)
        return True

    def chaos(self, kind: str, duration_s: float = 3.0, slow_s: float = 0.0):
        """Cooperative fault injection hook for
        ray_tpu.chaos.ServeChaosInjector ("hang" / "slow"); kills go
        straight to the OS. Test/bench surface — never on a request
        path."""
        now = time.time()
        if kind == "hang":
            self._wedged_until = now + duration_s
        elif kind == "slow":
            self._slow_until = now + duration_s
            self._slow_s = slow_s
        else:
            raise ValueError(f"unknown chaos kind {kind!r}")
        return True

    def stats(self):
        return {
            "num_requests": self.num_requests,
            "ongoing": self._ongoing,
            "queued": self._instance_load(),
            "load": self._load(),
            "pid": self._pid,
        }


@ray_tpu.remote
class ServeControllerActor:
    def __init__(self):
        from ray_tpu.serve.deployment_scheduler import DeploymentScheduler

        # app -> deployment -> record
        self.apps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.routes: Dict[str, tuple] = {}  # route_prefix -> (app, deployment)
        self._counter = 0
        self._scheduler = DeploymentScheduler()
        # node-grouped order the last upgrade drained in (introspection)
        self._last_drain_order: List[List[str]] = []
        # long-poll state: key -> monotonically increasing version; parked
        # listeners wake on bump (reference: LongPollHost notify_changed)
        self._versions: Dict[str, int] = {}
        self._events: Dict[str, Any] = {}
        self._loop_started = False
        # per-deployment autoscaler decision state (flap-guard timers +
        # smoothing windows), reset on redeploy
        self._autoscalers: Dict[tuple, Any] = {}
        # replica lifecycle state: birth times (startup grace for the
        # staleness check) + per-deployment crash/restart breakers
        self._knobs = _health_knobs()
        self._born: Dict[str, float] = {}
        self._breakers: Dict[tuple, Any] = {}
        # telemetry snapshot shared between the autoscale and health
        # loops: both tick at ~1s, so without the cache the controller
        # would pay two identical full-table GCS fetches per second
        self._stats_cache: tuple = (0.0, {})
        # SLO plane: per-deployment evaluator state (burn windows +
        # cumulative good/bad), the lost-request ledger (in-flight
        # estimates of replicas declared dead — the bad-request source
        # engines can't count themselves), and the flight-recorder
        # post-mortems read off SIGKILLed replicas' /dev/shm rings
        self._slo_states: Dict[tuple, Any] = {}
        self._lost: Dict[tuple, int] = {}
        self._postmortems: Dict[tuple, List[dict]] = {}

    # ------------------------------------------------------------ long poll
    def _bump(self, key: str):
        import asyncio

        self._versions[key] = self._versions.get(key, 0) + 1
        ev = self._events.get(key)
        if ev is not None:
            ev.set()
            self._events[key] = asyncio.Event()

    def _event_for(self, key: str):
        import asyncio

        ev = self._events.get(key)
        if ev is None:
            ev = self._events[key] = asyncio.Event()
        return ev

    async def listen_for_change(self, snapshot: Dict[str, int], timeout_s: float = 30.0):
        """Park until any key's version moves past the caller's snapshot;
        returns {key: {"version": v, "data": payload}} for changed keys
        (empty dict on timeout — caller re-issues)."""
        import asyncio

        deadline = time.monotonic() + timeout_s
        while True:
            changed = {
                key: {"version": self._versions.get(key, 0), "data": self._payload(key)}
                for key, ver in snapshot.items()
                if self._versions.get(key, 0) != ver
            }
            if changed:
                return changed
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {}
            waiters = [asyncio.ensure_future(self._event_for(key).wait()) for key in snapshot]
            done, pending = await asyncio.wait(
                waiters, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
            )
            for p in pending:
                p.cancel()
            if not done:
                return {}

    def _payload(self, key: str):
        if key == "routes":
            return dict(self.routes)
        if key.startswith("replicas::"):
            _, app, dep = key.split("::", 2)
            rec = self.apps.get(app, {}).get(dep)
            if rec is None:
                return []
            # membership + routing/failure config in one long-poll
            # payload, so a handle learns the deployment's affinity AND
            # redispatch policy the same push that tells it which
            # replicas exist
            return {
                "replicas": list(rec["replicas"]),
                "affinity": rec.get("affinity"),
                "fault": rec.get("fault"),
                # replica -> pool role, so handles build per-role
                # routing sub-rings from the same membership push
                "roles": dict(rec.get("roles") or {}),
            }
        return None

    # ------------------------------------------------------------ deploy
    async def deploy(
        self,
        app_name: str,
        deployment_name: str,
        cls_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        num_replicas: int,
        route_prefix: Optional[str],
        ray_actor_options: Optional[dict] = None,
        autoscaling_config: Optional[dict] = None,
        is_ingress: bool = False,
        affinity_config: Optional[dict] = None,
        fault_config: Optional[dict] = None,
        pool_config: Optional[dict] = None,
        slo_config: Optional[dict] = None,
    ):
        import cloudpickle

        from ray_tpu.serve._internal.autoscaler import (
            AutoscalingConfig,
            validate_affinity_config,
            validate_autoscaling_config,
            validate_fault_config,
            validate_pool_config,
        )
        from ray_tpu.serve._internal.slo import validate_slo_config

        cls = cloudpickle.loads(cls_blob)
        # normalize here too (defense in depth — serve.deployment()
        # already validated, but the controller RPC is also a surface)
        autoscaling_config = validate_autoscaling_config(autoscaling_config)
        affinity_config = validate_affinity_config(affinity_config)
        fault_config = validate_fault_config(fault_config)
        pool_config = validate_pool_config(pool_config)
        slo_config = validate_slo_config(slo_config)
        app = self.apps.setdefault(app_name, {})
        old = app.get(deployment_name)
        rec = {
            "cls": cls,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "replicas": [],
            "num_replicas": num_replicas,
            "route_prefix": route_prefix,
            "ray_actor_options": dict(ray_actor_options or {}),
            "autoscaling": autoscaling_config,
            "affinity": affinity_config,
            "fault": fault_config,
            # disaggregated pools: per-role replica targets + the live
            # replica -> role map (kv_plane; None/{} for plain deploys)
            "pools": pool_config,
            "roles": {},
            # serving objectives (slo.SloConfig shape); the control loop
            # runs an evaluator tick for deployments that set one
            "slo": slo_config,
            "is_ingress": is_ingress,
            "deploy_time": time.time(),
        }
        # fresh decision state on EVERY redeploy (also when autoscaling
        # was just turned off — status() must stop reporting the stale
        # autoscaler block): old flap-guard timers and load samples must
        # not drive the first decisions against the new replica set.
        # Pooled deployments key their states (app, dep, role).
        for key in [k for k in self._autoscalers
                    if k[0] == app_name and k[1] == deployment_name]:
            self._autoscalers.pop(key, None)
        # new code, new crash history: a redeploy closes the old
        # version's crash-loop breaker
        self._breakers.pop((app_name, deployment_name), None)
        # new objectives, fresh burn windows and lost-request ledger —
        # the old version's error budget must not bill the new one
        self._slo_states.pop((app_name, deployment_name), None)
        self._lost.pop((app_name, deployment_name), None)
        if autoscaling_config and not pool_config:
            rec["num_replicas"] = AutoscalingConfig(**autoscaling_config).start_replicas
        # stage new replicas BEFORE committing the record: a failed deploy
        # (e.g. __init__ raises) must leave the previous version serving
        import asyncio

        if pool_config:
            for role, n in pool_config.items():
                self._scale_pool(app_name, deployment_name, rec, role, n)
        else:
            self._scale_to(app_name, deployment_name, rec["num_replicas"], rec=rec)
        try:
            await asyncio.gather(
                *(ray_tpu.get_actor(name).health.remote() for name in rec["replicas"])
            )
        except Exception:
            for name in rec["replicas"]:
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:
                    pass
            raise
        app[deployment_name] = rec
        if old:
            # versioned in-place upgrade: the new replica set is healthy
            # and published FIRST (long-poll bump below swaps handles and
            # proxies over), then old replicas DRAIN their in-flight
            # requests before dying — a config redeploy must not drop
            # requests (reference: serve rolling updates +
            # graceful_shutdown_wait_loop_s)
            doomed = [n for n in old["replicas"] if n not in rec["replicas"]]
            # node-by-node rolling drain: one node's old replicas finish
            # their in-flight requests and die before the next node's are
            # touched (reference: serve drain-aware rolling updates)
            groups = self._scheduler.drain_groups(doomed)
            self._last_drain_order = groups

            async def _drain_by_node():
                for grp in groups:
                    await asyncio.gather(*(self._drain_and_kill(n) for n in grp))

            asyncio.ensure_future(_drain_by_node())
        if route_prefix:
            self.routes[route_prefix] = (app_name, deployment_name, is_ingress)
            self._bump("routes")
        self._bump(f"replicas::{app_name}::{deployment_name}")
        return True

    def _scale_to(self, app_name: str, deployment_name: str, target: int,
                  rec=None, loads: Optional[Dict[str, float]] = None):
        import asyncio

        rec = rec if rec is not None else self.apps[app_name][deployment_name]
        cur = list(rec["replicas"])
        while len(cur) < target:
            self._counter += 1
            name = f"SERVE_REPLICA::{app_name}::{deployment_name}::{self._counter}"
            # placement policy: spread by default, pack TPU replicas
            # (reference: serve/_private/deployment_scheduler.py)
            opts = self._scheduler.place(name, rec["ray_actor_options"])
            Replica.options(name=name, max_concurrency=16, **opts).remote(
                rec["cls"], rec["init_args"], rec["init_kwargs"], name
            )
            self._born[name] = time.time()
            cur.append(name)
        if len(cur) > target:
            # victim selection: least-loaded first (shortest drain, and
            # the requests it strands are fewest), newest first on ties
            # (the oldest replicas carry the hottest radix caches —
            # affinity traffic keeps landing there)
            n_kill = len(cur) - target
            victims = self._scheduler.downscale_order(cur, loads)[:n_kill]
            for name in victims:
                cur.remove(name)
                # drain before killing: the replica may still be serving
                # accepted requests (reference:
                # graceful_shutdown_wait_loop_s)
                asyncio.ensure_future(self._drain_and_kill(name))
        rec["replicas"] = cur
        rec["num_replicas"] = target

    def _scale_pool(self, app_name: str, dep_name: str, rec, role: str,
                    target: int, loads: Optional[Dict[str, float]] = None):
        """Scale ONE pool of a disaggregated deployment to `target`
        replicas. Same spawn/drain mechanics as _scale_to, restricted to
        the replicas whose role matches; new replicas get the role
        injected as the deployment's `pool` init kwarg, so the same user
        class serves both sides of the KV plane. Callers own
        rec["pools"][role] — a probe restart must not lower the stored
        target."""
        import asyncio

        roles = rec.setdefault("roles", {})
        cur = [n for n in rec["replicas"] if roles.get(n) == role]
        while len(cur) < target:
            self._counter += 1
            name = f"SERVE_REPLICA::{app_name}::{dep_name}::{self._counter}"
            opts = self._scheduler.place(name, rec["ray_actor_options"])
            kw = dict(rec["init_kwargs"])
            kw["pool"] = role
            Replica.options(name=name, max_concurrency=16, **opts).remote(
                rec["cls"], rec["init_args"], kw, name
            )
            self._born[name] = time.time()
            cur.append(name)
            rec["replicas"].append(name)
            roles[name] = role
        if len(cur) > target:
            n_kill = len(cur) - target
            victims = self._scheduler.downscale_order(cur, loads)[:n_kill]
            for name in victims:
                cur.remove(name)
                rec["replicas"].remove(name)
                roles.pop(name, None)
                asyncio.ensure_future(self._drain_and_kill(name))
        rec["num_replicas"] = len(rec["replicas"])

    async def _drain_and_kill(self, name: str, timeout_s: Optional[float] = None):
        import asyncio

        if timeout_s is None:
            # the cap exists for WEDGED replicas, not as a routine drop
            # window: autoscaler downscales are an everyday event, so a
            # request merely slower than the cap (long generation, cold
            # compile) must survive it — 60s default, env-overridable
            import os

            timeout_s = float(
                os.environ.get("RAY_TPU_SERVE_DRAIN_TIMEOUT_S", "60.0")
            )
        deadline = time.monotonic() + timeout_s
        try:
            h = ray_tpu.get_actor(name)
            while time.monotonic() < deadline:
                stats = await h.stats.remote()
                # queued covers async engines whose requests outlive
                # handle_request (in-flight work handle_request already
                # returned from must finish before the kill)
                if stats["ongoing"] == 0 and stats.get("queued", 0) == 0:
                    # double-check after a grace beat: a request routed
                    # in the membership-swap window may still be in
                    # transit toward this replica
                    await asyncio.sleep(0.3)
                    stats = await h.stats.remote()
                    if stats["ongoing"] == 0 and stats.get("queued", 0) == 0:
                        break
                await asyncio.sleep(0.25)
        except Exception:
            pass
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except Exception:
            pass
        self._scheduler.forget(name)
        # replica names are never reused: drop the birth stamp or the
        # dict grows one entry per replica a long-lived autoscaling
        # deployment ever scaled through
        self._born.pop(name, None)

    # ------------------------------------------------------ autoscale loop
    async def _fetch_replica_stats_shared(self, max_age_s: float = 0.5):
        """The ONE controller→GCS telemetry fetch per tick, shared by
        the autoscale and health loops through a short-lived cache (the
        blocking RPC runs off the actor's event loop)."""
        import asyncio

        t, stats = self._stats_cache
        now = time.monotonic()
        if now - t <= max_age_s:
            return stats
        stats = await asyncio.get_running_loop().run_in_executor(
            None, _fetch_replica_stats)
        self._stats_cache = (time.monotonic(), stats)
        return stats

    async def run_control_loop(self, period_s: float = 1.0):
        """Traffic-driven autoscaling (fire-and-forget from serve.run).

        Each tick makes ONE GCS round trip for the merged per-replica
        stat snapshots (published by the replicas themselves through the
        telemetry path — the loop never calls into a replica
        synchronously, so a wedged replica can't stall scaling for the
        whole cluster), then runs every autoscaled deployment's policy
        on host-side state only."""
        import asyncio

        if self._loop_started:
            return
        self._loop_started = True
        # replica lifecycle rides its own loop: health checking must not
        # share a tick budget with autoscaling (a suspect ping waits up
        # to ping_timeout_s; scaling decisions shouldn't)
        asyncio.ensure_future(self._health_loop(period_s))
        while True:
            await asyncio.sleep(period_s)
            deps_all = [
                (app_name, dep_name, rec)
                for app_name, deps in list(self.apps.items())
                for dep_name, rec in list(deps.items())
            ]
            targets = [t for t in deps_all if t[2].get("autoscaling")]
            slo_targets = [t for t in deps_all if t[2].get("slo")]
            if not targets and not slo_targets:
                continue
            # ONE GCS round trip per tick (_fetch_replica_stats via the
            # shared cache — the health loop and the SLO evaluator reuse
            # the same snapshot)
            stats = await self._fetch_replica_stats_shared()
            now = time.time()
            for app_name, dep_name, rec in targets:
                try:
                    self._autoscale_one(app_name, dep_name, rec, stats, now)
                except Exception:
                    import logging

                    logging.getLogger("ray_tpu.serve").warning(
                        "autoscale cycle failed for %s::%s", app_name, dep_name, exc_info=True
                    )
            for app_name, dep_name, rec in slo_targets:
                try:
                    self._slo_one(app_name, dep_name, rec, stats, now)
                except Exception:
                    import logging

                    logging.getLogger("ray_tpu.serve").warning(
                        "slo cycle failed for %s::%s", app_name, dep_name, exc_info=True
                    )

    def _autoscale_one(self, app_name, dep_name, rec, stats, now):
        """One deployment's autoscaling decision — synchronous, fed
        entirely from the telemetry snapshot (`stats`): no replica RPCs,
        no awaits. Scale-downs hand the policy's per-replica loads to
        the scheduler so the least-loaded replicas drain first."""
        from ray_tpu.serve._internal.autoscaler import AutoscalerState

        if rec.get("pools"):
            self._autoscale_pools(app_name, dep_name, rec, stats, now)
            return
        key = (app_name, dep_name)
        state = self._autoscalers.get(key)
        if state is None:
            state = self._autoscalers[key] = AutoscalerState(rec["autoscaling"])
        cfg = state.cfg
        current = len(rec["replicas"])
        if current == 0:
            # scaled to zero: handles PARK requests and report
            # starvation; a recent report is the demand signal that
            # wakes the deployment back up
            if cfg.min_replicas > 0 or (
                now - rec.get("starved_at", 0.0) <= STARVED_WINDOW_S
            ):
                self._scale_to(app_name, dep_name, max(cfg.min_replicas, 1))
                state.reset()
                self._bump(f"replicas::{app_name}::{dep_name}")
            return
        loads: Dict[str, float] = {}
        total = 0.0
        for name in rec["replicas"]:
            s = stats.get(name)
            if s and now - float(s.get("t", 0.0)) <= STATS_STALE_S:
                load = float(s.get("load", 0.0))
            else:
                # missing/stale stats are NEUTRAL: the replica counts as
                # exactly at target, so absent data never drives a scale
                # decision in either direction
                load = cfg.target_ongoing_requests
            loads[name] = load
            total += load
        desired = state.decide(total, current, now)
        if desired != current:
            self._scale_to(app_name, dep_name, desired, loads=loads)
            self._bump(f"replicas::{app_name}::{dep_name}")
        try:
            from ray_tpu import observability

            observability.publish_snapshot("serve", {
                f"autoscaler:{app_name}::{dep_name}": {
                    "t": now,
                    "replicas": len(rec["replicas"]),
                    "load": round(state.last_load, 3),
                    "desired": state.last_desired,
                    "min_replicas": cfg.min_replicas,
                    "max_replicas": cfg.max_replicas,
                    "target_ongoing_requests": cfg.target_ongoing_requests,
                }
            })
        except Exception:
            pass

    def _autoscale_pools(self, app_name, dep_name, rec, stats, now):
        """Per-pool autoscaling for a disaggregated deployment: the two
        pools scale INDEPENDENTLY on their own signals — prefill on
        queued prompt tokens (arrival burst pressure), decode on busy
        token-loop lanes (resident occupancy) — each through its own
        AutoscalerState keyed (app, dep, role), so a prompt burst grows
        the prefill pool without inflating the decode pool it will only
        trickle into."""
        from ray_tpu.serve._internal.autoscaler import (
            AutoscalerState,
            pool_autoscaler_config,
        )

        roles = rec.get("roles") or {}
        changed = False
        for role in list(rec["pools"]):
            key = (app_name, dep_name, role)
            state = self._autoscalers.get(key)
            if state is None:
                state = self._autoscalers[key] = AutoscalerState(
                    pool_autoscaler_config(rec["autoscaling"], role))
            cfg = state.cfg
            members = [n for n in rec["replicas"] if roles.get(n) == role]
            current = len(members)
            if current == 0:
                continue  # the health loop refills toward pools[role]
            signal_key = ("queued_prefill_tokens" if role == "prefill"
                          else "decode_lanes_busy")
            loads: Dict[str, float] = {}
            total = 0.0
            for name in members:
                s = stats.get(name)
                sig = s.get("pool_signals") if isinstance(s, dict) else None
                if (isinstance(sig, dict)
                        and now - float(s.get("t", 0.0)) <= STATS_STALE_S):
                    load = float(sig.get(signal_key, 0.0))
                else:
                    # missing/stale: neutral, exactly at target
                    load = cfg.target_ongoing_requests
                loads[name] = load
                total += load
            desired = state.decide(total, current, now)
            if desired != current:
                self._scale_pool(app_name, dep_name, rec, role, desired,
                                 loads=loads)
                rec["pools"][role] = desired
                changed = True
            try:
                from ray_tpu import observability

                observability.publish_snapshot("serve", {
                    f"autoscaler:{app_name}::{dep_name}::{role}": {
                        "t": now,
                        "pool": role,
                        "replicas": current,
                        "signal": signal_key,
                        "load": round(state.last_load, 3),
                        "desired": state.last_desired,
                        "min_replicas": cfg.min_replicas,
                        "max_replicas": cfg.max_replicas,
                        "target": cfg.target_ongoing_requests,
                    }
                })
            except Exception:
                pass
        if changed:
            self._bump(f"replicas::{app_name}::{dep_name}")

    # ------------------------------------------------------------ SLO plane
    def _slo_one(self, app_name, dep_name, rec, stats, now):
        """One deployment's SLO evaluator tick — synchronous arithmetic
        over the shared telemetry snapshot, no replica RPCs. Joins the
        deployment's live replicas to their engine metric snapshots by
        pid (engine reporters are named `llm-<pid>`; replica payloads
        carry "pid"), folds them plus the lost-request ledger into the
        SloState, and publishes the `slo:<app>::<dep>` snapshot that
        /api/serve, serve.status() and loadgen read."""
        from ray_tpu.serve._internal.slo import SloState, fold_engine_metrics

        key = (app_name, dep_name)
        state = self._slo_states.get(key)
        if state is None or state.cfg != rec["slo"]:
            state = self._slo_states[key] = SloState(rec["slo"])
        engines_all = stats.get("__engines__") or {}
        engines: Dict[str, Dict[str, Any]] = {}
        for name in rec["replicas"]:
            s = stats.get(name)
            pid = s.get("pid") if isinstance(s, dict) else None
            if pid is None:
                continue
            m = engines_all.get(f"llm-{pid}")
            if isinstance(m, dict):
                engines[name] = m
        folded = fold_engine_metrics(engines, lost_requests=self._lost.get(key, 0))
        state.observe(folded["good"], folded["bad"],
                      ttft_p99_ms=folded["ttft_p99_ms"],
                      tpot_p99_ms=folded["tpot_p99_ms"], now=now)
        try:
            from ray_tpu import observability

            observability.publish_snapshot("serve", {
                f"slo:{app_name}::{dep_name}": state.snapshot(now)
            })
        except Exception:
            pass

    # ------------------------------------------------------ replica health
    def _breaker(self, app_name: str, dep_name: str):
        from ray_tpu.serve._internal.lifecycle import CrashLoopBreaker

        key = (app_name, dep_name)
        b = self._breakers.get(key)
        if b is None:
            k = self._knobs
            b = self._breakers[key] = CrashLoopBreaker(
                backoff_base_s=k["restart_backoff_s"],
                window_s=k["crash_loop_window_s"],
                threshold=int(k["crash_loop_threshold"]),
                cooldown_s=k["breaker_cooldown_s"],
            )
        return b

    async def _health_loop(self, period_s: float = 1.0):
        """Replica lifecycle loop: telemetry-staleness + bounded ping
        health checks, dead/wedged replica replacement with exponential
        backoff and a crash-loop circuit breaker, state transitions
        published on /api/serve (`lifecycle:<app>::<dep>` snapshots).

        Steady-state cost: one GCS telemetry fetch + one actor-table
        fetch per tick, ZERO replica RPCs — pings go only to SUSPECTS
        (stale telemetry past the startup grace), each bounded by
        ping_timeout_s and gathered concurrently."""
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(period_s)
            targets = [
                (app_name, dep_name, rec)
                for app_name, deps in list(self.apps.items())
                for dep_name, rec in list(deps.items())
                if rec["replicas"] or len(rec["replicas"]) < rec["num_replicas"]
            ]
            if not targets:
                continue
            stats = await self._fetch_replica_stats_shared()
            actor_states = await loop.run_in_executor(None, _fetch_actor_states)
            now = time.time()
            for app_name, dep_name, rec in targets:
                try:
                    await self._health_one(
                        app_name, dep_name, rec, stats, actor_states, now)
                except Exception:
                    import logging

                    logging.getLogger("ray_tpu.serve").warning(
                        "health cycle failed for %s::%s",
                        app_name, dep_name, exc_info=True,
                    )

    async def _health_one(self, app_name, dep_name, rec, stats, actor_states, now):
        """One deployment's health pass: classify replicas from the two
        fetched tables, ping the suspects, replace the dead."""
        import asyncio

        dead: List[tuple] = []
        suspects: List[str] = []
        for name in list(rec["replicas"]):
            if actor_states.get(name) == "DEAD":
                # the GCS already knows (worker process exited / actor
                # killed): no ping needed, fastest detection path
                dead.append((name, "process died"))
                continue
            s = stats.get(name)
            t = float(s.get("t", 0.0)) if isinstance(s, dict) else 0.0
            if t >= now - self._knobs["health_stale_s"]:
                continue  # fresh telemetry: healthy, zero RPCs
            if t <= 0.0 and now - self._born.get(name, now) < self._knobs["startup_grace_s"]:
                continue  # still initializing (model load / compile)
            suspects.append(name)
        if suspects:
            oks = await asyncio.gather(
                *(self._ping_replica(n) for n in suspects))
            for name, ok in zip(suspects, oks):
                if not ok:
                    dead.append((name, "health check timed out (wedged)"))
        # capture the victims' last stats BEFORE pruning telemetry: the
        # pid keys the post-mortem flight-recorder read, and the last
        # reported load is the in-flight estimate the SLO plane bills as
        # lost requests (engines can't count their own death)
        last_stats = {name: stats.get(name) for name, _ in dead
                      if isinstance(stats.get(name), dict)}
        for name, reason in dead:
            self._on_replica_death(app_name, dep_name, rec, name, reason, now)
            s = last_stats.get(name) or {}
            key = (app_name, dep_name)
            self._lost[key] = self._lost.get(key, 0) + max(
                1, int(float(s.get("load", 0.0) or 0.0)))
        if dead:
            self._bump(f"replicas::{app_name}::{dep_name}")
            loop = asyncio.get_running_loop()
            # post-mortem FIRST: read each victim's crash-surviving
            # flight-recorder ring from /dev/shm (survives SIGKILL; the
            # dead-pid GC only sweeps it at session teardown) so the
            # lifecycle snapshot published below carries the tail
            for name, reason in dead:
                pid = (last_stats.get(name) or {}).get("pid")
                if pid:
                    await loop.run_in_executor(
                        None, self._read_postmortem,
                        app_name, dep_name, name, int(pid), reason, now)
            # prune the corpses' telemetry NOW: the ≤120s GCS retention
            # window would otherwise let the autoscaler keep counting a
            # crashed replica's last-published load as live signal
            for name, _ in dead:
                loop.run_in_executor(None, _prune_replica_telemetry, name)
        self._maybe_restart(app_name, dep_name, rec, now)
        if dead:
            self._publish_lifecycle(app_name, dep_name, rec, now)

    def _read_postmortem(self, app_name, dep_name, name, pid, reason, now):
        """Blocking (executor-run) read of a dead replica's flight ring;
        stores the decoded tail for lifecycle snapshots + status()."""
        try:
            from ray_tpu.observability import flight_recorder

            tail = flight_recorder.read_tail(pid=pid, n=64)
        except Exception:
            tail = []
        key = (app_name, dep_name)
        pms = self._postmortems.setdefault(key, [])
        pms.append({"t": now, "replica": name, "pid": pid,
                    "reason": reason, "events": tail})
        del pms[:-4]  # bounded: keep the last few corpses per deployment

    async def _ping_replica(self, name: str) -> bool:
        """Bounded liveness ping for ONE suspect; False = wedged/dead."""
        import asyncio

        try:
            h = ray_tpu.get_actor(name)
            await asyncio.wait_for(
                h.health.remote(), timeout=self._knobs["ping_timeout_s"])
            return True
        except Exception:
            return False

    def _on_replica_death(self, app_name, dep_name, rec, name, reason, now):
        """Remove one dead/wedged replica from the serving set and
        record the crash. The membership bump (caller) makes handles
        stop routing at it; their in-flight requests fail through the
        transport/RPC death paths and funnel into the handle's
        redispatch choke point."""
        import logging

        logging.getLogger("ray_tpu.serve").warning(
            "replica %s declared dead (%s); removing from %s/%s",
            name, reason, app_name, dep_name,
        )
        if name in rec["replicas"]:
            rec["replicas"].remove(name)
        (rec.get("roles") or {}).pop(name, None)
        self._scheduler.forget(name)
        self._born.pop(name, None)
        try:
            # wedged replicas are still registered: kill so the restart
            # below doesn't race a zombie holding the old name's state
            ray_tpu.kill(ray_tpu.get_actor(name))
        except Exception:
            pass
        self._breaker(app_name, dep_name).record_crash(name, now, reason)

    def _maybe_restart(self, app_name, dep_name, rec, now):
        """Refill the replica set toward its target, gated by the
        deployment's backoff/breaker state. In the breaker's half-open
        phase exactly ONE probe replica starts — the rest of the
        target waits until the probe survives its window (a
        num_replicas=N crash-looper must not pay N doomed spawns per
        cooldown cycle)."""
        pools = rec.get("pools")
        if pools:
            # pooled refill: deficits are PER ROLE (a dead decode
            # replica must come back as a decode replica); the breaker
            # stays deployment-wide — a crash-looping class crash-loops
            # in both roles
            roles = rec.get("roles") or {}
            counts = {r: 0 for r in pools}
            for n in rec["replicas"]:
                r = roles.get(n)
                if r in counts:
                    counts[r] += 1
            deficits = {r: pools[r] - counts[r]
                        for r in pools if pools[r] > counts[r]}
            if not deficits:
                return
            breaker = self._breaker(app_name, dep_name)
            at = breaker.restart_at(now)
            if at is None or at > now:
                return
            before = list(rec["replicas"])
            if breaker.probing(now):
                role = next(iter(deficits))
                self._scale_pool(app_name, dep_name, rec, role,
                                 counts[role] + 1)
            else:
                for role in deficits:
                    self._scale_pool(app_name, dep_name, rec, role,
                                     pools[role])
            rec["num_replicas"] = len(rec["replicas"])
            for name in rec["replicas"]:
                if name not in before:
                    breaker.record_restart(name, now)
            self._bump(f"replicas::{app_name}::{dep_name}")
            self._publish_lifecycle(app_name, dep_name, rec, now)
            return
        desired = rec["num_replicas"]
        missing = desired - len(rec["replicas"])
        if missing <= 0:
            return
        breaker = self._breaker(app_name, dep_name)
        at = breaker.restart_at(now)
        if at is None or at > now:
            return  # crash-looped / probe out (None) or still backing off
        target = min(desired, len(rec["replicas"]) + 1) \
            if breaker.probing(now) else desired
        before = list(rec["replicas"])
        self._scale_to(app_name, dep_name, target, rec=rec)
        # a probe scale must not lower the deployment's stored target
        rec["num_replicas"] = desired
        for name in rec["replicas"]:
            if name not in before:
                breaker.record_restart(name, now)
        self._bump(f"replicas::{app_name}::{dep_name}")
        self._publish_lifecycle(app_name, dep_name, rec, now)

    def _publish_lifecycle(self, app_name, dep_name, rec, now):
        """Replica state transitions on /api/serve: the
        `lifecycle:<app>::<dep>` snapshot carries the breaker state and
        the recent died/restarted/breaker event log."""
        try:
            from ray_tpu import observability

            breaker = self._breaker(app_name, dep_name)
            payload = {
                "t": now,
                "replicas": len(rec["replicas"]),
                "target": rec["num_replicas"],
                **breaker.state(now),
            }
            pms = self._postmortems.get((app_name, dep_name))
            if pms:
                # the most recent corpse's flight-recorder tail rides
                # the lifecycle snapshot: "the replica died" comes with
                # "and here is what it was doing"
                payload["postmortem"] = pms[-1]
            observability.publish_snapshot("serve", {
                f"lifecycle:{app_name}::{dep_name}": payload
            })
        except Exception:
            pass

    async def notify_starved(self, app_name: str, dep_name: str):
        """A handle is parking requests against an empty replica set —
        the scale-from-zero demand signal (rate-limited caller-side)."""
        rec = self.apps.get(app_name, {}).get(dep_name)
        if rec is not None:
            rec["starved_at"] = time.time()
        return True

    # ------------------------------------------------------------- queries
    async def get_replicas_versioned(self, app_name: str, deployment_name: str):
        key = f"replicas::{app_name}::{deployment_name}"
        return {"version": self._versions.get(key, 0), "data": self._payload(key)}

    async def get_routes(self) -> Dict[str, tuple]:
        return dict(self.routes)

    async def last_drain_order(self) -> List[List[str]]:
        """Node-grouped replica names the last upgrade drained in order."""
        return self._last_drain_order

    async def replica_placements(self) -> Dict[str, str]:
        """replica name -> node id chosen by the deployment scheduler."""
        return dict(self._scheduler._placed)

    async def delete_app(self, app_name: str):
        app = self.apps.pop(app_name, None)
        if not app:
            return False
        for key in [k for k in self._autoscalers if k[0] == app_name]:
            self._autoscalers.pop(key, None)
        for key in [k for k in self._breakers if k[0] == app_name]:
            self._breakers.pop(key, None)
        for d in (self._slo_states, self._lost, self._postmortems):
            for key in [k for k in d if k[0] == app_name]:
                d.pop(key, None)
        for dep_name, dep in app.items():
            for name in dep["replicas"]:
                self._born.pop(name, None)
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:
                    pass
            if dep.get("route_prefix"):
                self.routes.pop(dep["route_prefix"], None)
            self._bump(f"replicas::{app_name}::{dep_name}")
        self._bump("routes")
        return True

    async def status(self) -> Dict[str, Any]:
        out = {}
        for app_name, deps in self.apps.items():
            out[app_name] = {}
            for name, d in deps.items():
                entry = {
                    "num_replicas": len(d["replicas"]),
                    "route_prefix": d["route_prefix"],
                    "autoscaling": bool(d.get("autoscaling")),
                }
                state = self._autoscalers.get((app_name, name))
                if state is not None:
                    entry["autoscaler"] = {
                        "load": round(state.last_load, 3),
                        "desired": state.last_desired,
                        "min_replicas": state.cfg.min_replicas,
                        "max_replicas": state.cfg.max_replicas,
                    }
                if d.get("affinity"):
                    entry["affinity"] = dict(d["affinity"])
                if d.get("fault"):
                    entry["fault"] = dict(d["fault"])
                if d.get("pools"):
                    roles = d.get("roles") or {}
                    entry["pools"] = {
                        role: {
                            "target": n,
                            "replicas": sum(
                                1 for x in d["replicas"]
                                if roles.get(x) == role),
                        }
                        for role, n in d["pools"].items()
                    }
                breaker = self._breakers.get((app_name, name))
                if breaker is not None and breaker.events:
                    st = breaker.state()
                    entry["lifecycle"] = {
                        "state": st["state"],
                        "recent_crashes": st["recent_crashes"],
                    }
                slo_state = self._slo_states.get((app_name, name))
                if slo_state is not None:
                    entry["slo"] = slo_state.snapshot()
                pms = self._postmortems.get((app_name, name))
                if pms:
                    entry["postmortem"] = pms[-1]
                out[app_name][name] = entry
        return out

    async def request_timeline(self, rid: str) -> List[Dict[str, Any]]:
        """Cluster-wide lifeline for one request id: fan the per-replica
        `request_timeline` out to every live replica of every deployment
        and merge by timestamp — the prefill-side events, the KV-plane
        hop and the decode-side resume stitch into ONE timeline because
        the rid survives migration and redispatch end-to-end. Dead
        replicas' contributions come from post-mortem flight-ring tails
        (matched by rid) instead."""
        import asyncio

        names = [n for deps in self.apps.values()
                 for rec in deps.values() for n in rec["replicas"]]

        async def _one(name):
            try:
                h = ray_tpu.get_actor(name)
                evs = await asyncio.wait_for(
                    h.handle_request.remote("request_timeline", (rid,), {}),
                    timeout=5.0)
                for e in evs or []:
                    e.setdefault("replica", name)
                return evs or []
            except Exception:
                return []

        merged: List[Dict[str, Any]] = []
        for evs in await asyncio.gather(*(_one(n) for n in names)):
            merged.extend(evs)
        # dead replicas: their in-memory lifelines died with them, but
        # the flight-ring post-mortems carry rid-stamped records
        for pms in self._postmortems.values():
            for pm in pms:
                for e in pm.get("events", []):
                    # ring records carry the rid's first 24 bytes
                    if e.get("rid") and e["rid"] == rid[:24]:
                        ev = dict(e)
                        ev["replica"] = pm.get("replica")
                        ev["postmortem"] = True
                        merged.append(ev)
        merged.sort(key=lambda e: e.get("t", 0.0))
        return merged
