"""Batched LLM serving deployment.

The packaged form of the TPU LLM-serving shape (the reference serves
LLMs through external engines inside replicas — vLLM in its examples;
here the engine is the jitted prefill + device-side decode loop from
models/llama_decode). Concurrent requests coalesce through
@serve.batch; within a batch, prompts are grouped by length so each
group runs one prefill + one lax.scan decode with static shapes and no
padding/masking complications. Shape churn is bounded by rounding
prompt-group lengths up to a bucket multiple, so the jit cache stays
small and warm.

Requests on the continuous path are either a bare token list (greedy,
engine defaults) or a dict carrying per-request SamplingParams fields:

    handle.remote({"prompt": [1, 2, 3], "temperature": 0.7,
                   "top_p": 0.9, "seed": 42, "stop": [2],
                   "max_new_tokens": 64, "session_id": "user-7"})

`session_id` is routing-only: with an `affinity_config` on the
deployment, the handle hashes it (or the prompt prefix) so a session's
repeat traffic lands on the replica whose radix cache is hot.

temperature/top-k/top-p sampling and stop tokens require the paged
engine (`paged=True`, the default for `continuous=True`) — they run
device-side inside the decode scan (models/llama_decode.sample_tokens).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.serve._internal.sampling import SamplingParams
from ray_tpu.serve.api import batch, deployment


def _parse_request(req, default_max_new: int):
    """Request-path coercion: bare prompt list or dict with sampling
    fields -> (prompt, max_new_tokens, SamplingParams, request_id)."""
    if isinstance(req, dict):
        body = dict(req)
        if "prompt" not in body:
            raise ValueError(
                f"dict request must carry a 'prompt' field "
                f"(got keys {sorted(body)})"
            )
        prompt = [int(t) for t in body.pop("prompt")]
        max_new = int(body.pop("max_new_tokens", default_max_new))
        # routing-only field: the handle/proxy affinity layer hashes it
        # to pick a cache-hot replica; the engine itself ignores it
        body.pop("session_id", None)
        # caller-generated request id (redispatch bookkeeping / logs)
        rid = body.pop("request_id", None)
        # relative deadline form: the handle normally stamps the
        # absolute `deadline` at submit (so a redispatch can't reset
        # the clock); direct engine callers may still pass deadline_s
        deadline_s = body.pop("deadline_s", None)
        if deadline_s is not None and body.get("deadline") is None:
            import time

            body["deadline"] = time.time() + float(deadline_s)
        known = {f.name for f in dataclasses.fields(SamplingParams)}
        unknown = set(body) - known
        if unknown:
            raise ValueError(
                f"unknown request field(s) {sorted(unknown)}; valid "
                f"sampling fields: {sorted(known)}"
            )
        return prompt, max_new, SamplingParams(**body), rid
    return [int(t) for t in req], default_max_new, SamplingParams(), None


class _LLMServer:
    """The deployment callable. Wrap with serve.deployment via
    `llm_deployment(...)` or subclass for custom param loading."""

    def __init__(self, cfg=None, params=None, max_new_tokens: int = 32,
                 checkpoint_dir: Optional[str] = None, seed: int = 0,
                 continuous: bool = False, n_slots: int = 8, chunk: int = 8,
                 macro_phases: int = 8, paged: Optional[bool] = None,
                 block_size: int = 16, n_blocks: int = 0,
                 prefix_cache: bool = True, max_queue: Optional[int] = None,
                 draft_model=None, num_speculative_tokens: int = 0):
        import jax

        from ray_tpu.models import llama

        self.cfg = cfg or llama.LlamaConfig.tiny()
        if params is not None:
            self.params = params
        elif checkpoint_dir is not None:
            from ray_tpu.train.orbax_utils import load_pytree_from_checkpoint

            self.params = load_pytree_from_checkpoint(checkpoint_dir)
        else:
            self.params = llama.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.max_new_tokens = max_new_tokens
        self.engine = None
        if continuous:
            # continuous batching: requests admit/evict per decode chunk,
            # with macro-step scheduling batching K chunks per dispatch;
            # paged (default) decouples KV memory from slots x max_len
            # and unlocks sampling + stop tokens + prefix reuse
            from ray_tpu.serve.llm_engine import ContinuousBatchingEngine

            if paged is None:
                # auto: paged whenever the macro scheduler runs; the
                # legacy per-chunk path (macro_phases=0) stays dense.
                # An EXPLICIT paged=True with macro_phases=0 is a config
                # error the engine raises loudly — never a silent
                # downgrade to dense.
                paged = macro_phases > 0
            import os

            self.engine = ContinuousBatchingEngine(
                self.params, self.cfg, n_slots=n_slots, chunk=chunk,
                macro_phases=macro_phases, paged=paged,
                block_size=block_size, n_blocks=n_blocks,
                prefix_cache=prefix_cache, max_queue=max_queue,
                # lossless draft-model speculation: draft_model is None
                # (off — the engine compiles the exact pre-speculation
                # program), "self", a LlamaConfig, or a dict with cfg +
                # params/checkpoint_dir/seed (see _internal/speculative)
                draft_model=draft_model,
                num_speculative_tokens=num_speculative_tokens,
                # pid-unique name: each replica's engine publishes its
                # own `engine:<name>` telemetry entry, so /api/serve
                # shows PER-REPLICA serving metrics (same-named engines
                # collide last-write-wins in the merged table)
                name=f"llm-{os.getpid()}",
            )

    def metrics(self) -> Dict[str, Any]:
        """Engine serving metrics (dispatches/token, lane occupancy,
        TTFT/TPOT percentiles); empty for the static-batching path."""
        return self.engine.metrics() if self.engine is not None else {}

    def __serve_load__(self) -> int:
        """Autoscaling load signal: the engine's resident + queued
        request count. The Replica wrapper publishes this through the
        telemetry path — with the direct-transport deferred-completion
        path, `handle_request` returns before generation finishes, so
        the replica's own in-flight counter can't see engine load."""
        return self.engine.load() if self.engine is not None else 0

    @batch(max_batch_size=32, batch_wait_timeout_s=0.02)
    def _generate(self, prompts: List[List[int]]) -> List[List[int]]:
        from ray_tpu.models import llama_decode

        # group by prompt length: each group is one static-shape
        # prefill + one device-side decode scan
        groups: Dict[int, List[int]] = {}
        for i, p in enumerate(prompts):
            groups.setdefault(len(p), []).append(i)
        out: List[Any] = [None] * len(prompts)
        for length, idxs in groups.items():
            arr = np.asarray([prompts[i] for i in idxs], np.int32)
            toks = llama_decode.generate(
                self.params, arr, self.cfg, max_new_tokens=self.max_new_tokens
            )
            for row, i in enumerate(idxs):
                out[i] = toks[row].tolist()
        return out

    def __call__(self, request) -> List[int]:
        if self.engine is not None:
            prompt, max_new, sampling, rid = _parse_request(
                request, self.max_new_tokens
            )
            from ray_tpu.experimental.direct_transport import maybe_defer

            deferred = maybe_defer()
            if deferred is not None:
                # direct-transport fast path: submit() enqueues onto the
                # engine loop and the completion notification rides the
                # reply ring FROM the engine loop thread — no replica
                # thread parks on the done event and the completion costs
                # one ring write instead of an object-store round trip
                def _complete(req):
                    if req.error is None:
                        deferred.complete(req.tokens)
                    else:
                        # typed failure when the engine recorded one
                        # (shed / deadline / replica-death) — the class
                        # crosses the ring pickled, so the handle's
                        # redispatch policy classifies by isinstance
                        deferred.fail(req.exc or RuntimeError(
                            f"generation failed: {req.error}"))

                # a submit() raise (dead engine, shed, bad request)
                # propagates: the transport surfaces it and disarms the
                # deferred
                self.engine.submit(
                    prompt, max_new, on_done=_complete, sampling=sampling,
                    rid=rid,
                )
                return None
            return self.engine.generate(prompt, max_new, sampling=sampling,
                                        rid=rid)
        if isinstance(request, dict):
            raise ValueError(
                "per-request sampling needs the continuous engine "
                "(llm_deployment(continuous=True))"
            )
        return self._generate([int(t) for t in request])


def llm_deployment(num_replicas: int = 1, max_new_tokens: int = 32,
                   cfg=None, checkpoint_dir: Optional[str] = None,
                   continuous: bool = False, n_slots: int = 8,
                   chunk: int = 8, macro_phases: int = 8,
                   paged: Optional[bool] = None, block_size: int = 16,
                   n_blocks: int = 0, prefix_cache: bool = True,
                   max_queue: Optional[int] = None, draft_model=None,
                   num_speculative_tokens: int = 0,
                   **deploy_kw):
    """A ready-to-run LLM generation application:

        app = llm_deployment(num_replicas=2, max_new_tokens=16)
        handle = serve.run(app, name="llm")
        handle.remote([1, 2, 3]).result()

    With continuous=True the replica runs the paged continuous-batching
    engine: requests may be dicts carrying SamplingParams fields
    (temperature/top_k/top_p/seed/stop/max_new_tokens, plus the
    relative `deadline_s` budget); `block_size` / `n_blocks` size the
    paged KV pool, `prefix_cache` toggles radix prompt-prefix reuse and
    `max_queue` bounds admission (excess requests shed with a typed
    retryable error instead of queueing unboundedly).

    `draft_model` + `num_speculative_tokens` turn on LOSSLESS
    draft-model speculative decoding (paged engine only): a small draft
    model proposes num_speculative_tokens tokens per lane each round
    and the target verifies them all in one batched dispatch, emitting
    every accepted token plus one correction/bonus token. Greedy output
    is bit-identical to non-speculative decoding and sampled output
    draws from the exact same distribution — the knob trades draft
    FLOPs for fewer target dispatches, it never changes results.
    `draft_model` accepts "self" (the target drafts for itself — only
    useful for testing), "self:N" (self-speculative truncation: the
    target's own first N layers draft, zero extra weights), a
    LlamaConfig (random init), or a dict of
    {"cfg": LlamaConfig, "checkpoint_dir"/"params"/"seed": ...}. With
    draft_model=None the replica compiles a program with zero draft
    FLOPs — speculation off costs nothing.

    Generation is side-effect-free, so the deployment opts into
    replica-death REDISPATCH by default: a request in flight on a
    SIGKILLed/wedged replica (from which no output can have escaped —
    results deliver only at completion) is requeued onto a survivor by
    the handle; pass fault_config={"redispatch": False} to disable."""
    deploy_kw.setdefault("fault_config", {"redispatch": True})
    dep = deployment(
        _LLMServer, name="LLMServer", num_replicas=num_replicas, **deploy_kw
    )
    return dep.bind(cfg=cfg, max_new_tokens=max_new_tokens,
                    checkpoint_dir=checkpoint_dir, continuous=continuous,
                    n_slots=n_slots, chunk=chunk, macro_phases=macro_phases,
                    paged=paged, block_size=block_size, n_blocks=n_blocks,
                    prefix_cache=prefix_cache, max_queue=max_queue,
                    draft_model=draft_model,
                    num_speculative_tokens=num_speculative_tokens)
