"""Batched LLM serving deployment.

The packaged form of the TPU LLM-serving shape (the reference serves
LLMs through external engines inside replicas — vLLM in its examples;
here the engine is the jitted prefill + device-side decode loop from
models/llama_decode). Concurrent requests coalesce through
@serve.batch; within a batch, prompts are grouped by length so each
group runs one prefill + one lax.scan decode with static shapes and no
padding/masking complications. Shape churn is bounded by rounding
prompt-group lengths up to a bucket multiple, so the jit cache stays
small and warm.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.serve.api import batch, deployment


class _LLMServer:
    """The deployment callable. Wrap with serve.deployment via
    `llm_deployment(...)` or subclass for custom param loading."""

    def __init__(self, cfg=None, params=None, max_new_tokens: int = 32,
                 checkpoint_dir: Optional[str] = None, seed: int = 0,
                 continuous: bool = False, n_slots: int = 8, chunk: int = 8,
                 macro_phases: int = 8):
        import jax

        from ray_tpu.models import llama

        self.cfg = cfg or llama.LlamaConfig.tiny()
        if params is not None:
            self.params = params
        elif checkpoint_dir is not None:
            from ray_tpu.train.orbax_utils import load_pytree_from_checkpoint

            self.params = load_pytree_from_checkpoint(checkpoint_dir)
        else:
            self.params = llama.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.max_new_tokens = max_new_tokens
        self.engine = None
        if continuous:
            # continuous batching: requests admit/evict per decode chunk,
            # with macro-step scheduling batching K chunks per dispatch
            from ray_tpu.serve.llm_engine import ContinuousBatchingEngine

            self.engine = ContinuousBatchingEngine(
                self.params, self.cfg, n_slots=n_slots, chunk=chunk,
                macro_phases=macro_phases,
            )

    def metrics(self) -> Dict[str, Any]:
        """Engine serving metrics (dispatches/token, lane occupancy,
        TTFT/TPOT percentiles); empty for the static-batching path."""
        return self.engine.metrics() if self.engine is not None else {}

    @batch(max_batch_size=32, batch_wait_timeout_s=0.02)
    def _generate(self, prompts: List[List[int]]) -> List[List[int]]:
        from ray_tpu.models import llama_decode

        # group by prompt length: each group is one static-shape
        # prefill + one device-side decode scan
        groups: Dict[int, List[int]] = {}
        for i, p in enumerate(prompts):
            groups.setdefault(len(p), []).append(i)
        out: List[Any] = [None] * len(prompts)
        for length, idxs in groups.items():
            arr = np.asarray([prompts[i] for i in idxs], np.int32)
            toks = llama_decode.generate(
                self.params, arr, self.cfg, max_new_tokens=self.max_new_tokens
            )
            for row, i in enumerate(idxs):
                out[i] = toks[row].tolist()
        return out

    def __call__(self, prompt: List[int]) -> List[int]:
        if self.engine is not None:
            from ray_tpu.experimental.direct_transport import maybe_defer

            deferred = maybe_defer()
            if deferred is not None:
                # direct-transport fast path: submit() enqueues onto the
                # engine loop and the completion notification rides the
                # reply ring FROM the engine loop thread — no replica
                # thread parks on the done event and the completion costs
                # one ring write instead of an object-store round trip
                def _complete(req):
                    if req.error is None:
                        deferred.complete(req.tokens)
                    else:
                        deferred.fail(RuntimeError(f"generation failed: {req.error}"))

                # a submit() raise (dead engine, bad request) propagates:
                # the transport surfaces it and disarms the deferred
                self.engine.submit(
                    [int(t) for t in prompt], self.max_new_tokens,
                    on_done=_complete,
                )
                return None
            return self.engine.generate(
                [int(t) for t in prompt], self.max_new_tokens
            )
        return self._generate([int(t) for t in prompt])


def llm_deployment(num_replicas: int = 1, max_new_tokens: int = 32,
                   cfg=None, checkpoint_dir: Optional[str] = None,
                   continuous: bool = False, n_slots: int = 8,
                   chunk: int = 8, macro_phases: int = 8, **deploy_kw):
    """A ready-to-run LLM generation application:

        app = llm_deployment(num_replicas=2, max_new_tokens=16)
        handle = serve.run(app, name="llm")
        handle.remote([1, 2, 3]).result()
    """
    dep = deployment(
        _LLMServer, name="LLMServer", num_replicas=num_replicas, **deploy_kw
    )
    return dep.bind(cfg=cfg, max_new_tokens=max_new_tokens,
                    checkpoint_dir=checkpoint_dir, continuous=continuous,
                    n_slots=n_slots, chunk=chunk, macro_phases=macro_phases)
