"""Batched LLM serving deployment.

The packaged form of the TPU LLM-serving shape (the reference serves
LLMs through external engines inside replicas — vLLM in its examples;
here the engine is the jitted prefill + device-side decode loop from
models/llama_decode). Concurrent requests coalesce through
@serve.batch; within a batch, prompts are grouped by length so each
group runs one prefill + one lax.scan decode with static shapes and no
padding/masking complications. Shape churn is bounded by rounding
prompt-group lengths up to a bucket multiple, so the jit cache stays
small and warm.

Requests on the continuous path are either a bare token list (greedy,
engine defaults) or a dict carrying per-request SamplingParams fields:

    handle.remote({"prompt": [1, 2, 3], "temperature": 0.7,
                   "top_p": 0.9, "seed": 42, "stop": [2],
                   "max_new_tokens": 64, "session_id": "user-7"})

`session_id` is routing-only: with an `affinity_config` on the
deployment, the handle hashes it (or the prompt prefix) so a session's
repeat traffic lands on the replica whose radix cache is hot.

temperature/top-k/top-p sampling and stop tokens require the paged
engine (`paged=True`, the default for `continuous=True`) — they run
device-side inside the decode scan (models/llama_decode.sample_tokens).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.serve._internal.sampling import SamplingParams
from ray_tpu.serve.api import batch, deployment


def _parse_request(req, default_max_new: int):
    """Request-path coercion: bare prompt list or dict with sampling
    fields -> (prompt, max_new_tokens, SamplingParams, request_id)."""
    if isinstance(req, dict):
        body = dict(req)
        if "prompt" not in body:
            raise ValueError(
                f"dict request must carry a 'prompt' field "
                f"(got keys {sorted(body)})"
            )
        prompt = [int(t) for t in body.pop("prompt")]
        max_new = int(body.pop("max_new_tokens", default_max_new))
        # routing-only field: the handle/proxy affinity layer hashes it
        # to pick a cache-hot replica; the engine itself ignores it
        body.pop("session_id", None)
        # caller-generated request id (redispatch bookkeeping / logs)
        rid = body.pop("request_id", None)
        # relative deadline form: the handle normally stamps the
        # absolute `deadline` at submit (so a redispatch can't reset
        # the clock); direct engine callers may still pass deadline_s
        deadline_s = body.pop("deadline_s", None)
        if deadline_s is not None and body.get("deadline") is None:
            import time

            body["deadline"] = time.time() + float(deadline_s)
        known = {f.name for f in dataclasses.fields(SamplingParams)}
        unknown = set(body) - known
        if unknown:
            raise ValueError(
                f"unknown request field(s) {sorted(unknown)}; valid "
                f"sampling fields: {sorted(known)}"
            )
        return prompt, max_new, SamplingParams(**body), rid
    return [int(t) for t in req], default_max_new, SamplingParams(), None


class _LLMServer:
    """The deployment callable. Wrap with serve.deployment via
    `llm_deployment(...)` or subclass for custom param loading."""

    def __init__(self, cfg=None, params=None, max_new_tokens: int = 32,
                 checkpoint_dir: Optional[str] = None, seed: int = 0,
                 continuous: bool = False, n_slots: int = 8, chunk: int = 8,
                 macro_phases: int = 8, paged: Optional[bool] = None,
                 block_size: int = 16, n_blocks: int = 0,
                 prefix_cache: bool = True, max_queue: Optional[int] = None,
                 draft_model=None, num_speculative_tokens: int = 0,
                 pool: Optional[str] = None,
                 cluster_cache: Optional[bool] = None,
                 digest_prefix_len: int = 32):
        import jax

        from ray_tpu.models import llama

        if pool is not None and not continuous:
            raise ValueError(
                "pool roles require the continuous engine "
                "(llm_deployment(continuous=True, pools=...))")

        self.cfg = cfg or llama.LlamaConfig.tiny()
        if params is not None:
            self.params = params
        elif checkpoint_dir is not None:
            from ray_tpu.train.orbax_utils import load_pytree_from_checkpoint

            self.params = load_pytree_from_checkpoint(checkpoint_dir)
        else:
            self.params = llama.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.max_new_tokens = max_new_tokens
        self.engine = None
        self.pool = pool
        self._digest_prefix_len = digest_prefix_len
        # KV-plane state (disaggregated serving): exported payload refs
        # pinned until the decode pool acks, the lazy handle back into
        # this deployment's decode pool, the migration pump threads, and
        # the prefetch memo that rate-limits cluster-cache fetch attempts
        self._export_refs: Any = None
        self._decode_h: Any = None
        self._pump: Any = None
        self._prefetch_memo: Dict[str, float] = {}
        if continuous:
            # continuous batching: requests admit/evict per decode chunk,
            # with macro-step scheduling batching K chunks per dispatch;
            # paged (default) decouples KV memory from slots x max_len
            # and unlocks sampling + stop tokens + prefix reuse
            from ray_tpu.serve.llm_engine import ContinuousBatchingEngine

            if paged is None:
                # auto: paged whenever the macro scheduler runs; the
                # legacy per-chunk path (macro_phases=0) stays dense.
                # An EXPLICIT paged=True with macro_phases=0 is a config
                # error the engine raises loudly — never a silent
                # downgrade to dense.
                paged = macro_phases > 0
            import os

            self.engine = ContinuousBatchingEngine(
                self.params, self.cfg, n_slots=n_slots, chunk=chunk,
                macro_phases=macro_phases, paged=paged,
                block_size=block_size, n_blocks=n_blocks,
                prefix_cache=prefix_cache, max_queue=max_queue,
                # lossless draft-model speculation: draft_model is None
                # (off — the engine compiles the exact pre-speculation
                # program), "self", a LlamaConfig, or a dict with cfg +
                # params/checkpoint_dir/seed (see _internal/speculative)
                draft_model=draft_model,
                num_speculative_tokens=num_speculative_tokens,
                # disaggregated pool role + cluster-wide prefix cache
                role=pool, cluster_cache=cluster_cache,
                digest_prefix_len=digest_prefix_len,
                # pid-unique name: each replica's engine publishes its
                # own `engine:<name>` telemetry entry, so /api/serve
                # shows PER-REPLICA serving metrics (same-named engines
                # collide last-write-wins in the merged table)
                name=f"llm-{os.getpid()}",
            )
            # label this process's lifeline events with the replica
            # coordinates when serving (the engine name otherwise) —
            # request_timeline shows WHERE each hop ran
            try:
                from ray_tpu.observability import lifeline
                from ray_tpu.serve._internal import kv_plane

                lifeline.set_process_label(
                    kv_plane.current_replica_name()
                    or f"llm-{os.getpid()}")
            except Exception:
                pass

    def metrics(self) -> Dict[str, Any]:
        """Engine serving metrics (dispatches/token, lane occupancy,
        TTFT/TPOT percentiles); empty for the static-batching path."""
        return self.engine.metrics() if self.engine is not None else {}

    def request_timeline(self, rid: str) -> List[Dict[str, Any]]:
        """This replica's slice of one request's lifeline — the
        controller fans this RPC out across replicas and merges by rid
        into the cluster-wide timeline (serve.request_timeline)."""
        if self.engine is not None:
            return self.engine.request_timeline(rid)
        from ray_tpu.observability import lifeline

        return lifeline.events(rid)

    def __serve_load__(self) -> int:
        """Autoscaling load signal: the engine's resident + queued
        request count. The Replica wrapper publishes this through the
        telemetry path — with the direct-transport deferred-completion
        path, `handle_request` returns before generation finishes, so
        the replica's own in-flight counter can't see engine load."""
        return self.engine.load() if self.engine is not None else 0

    # -- KV plane (disaggregated pools + cluster prefix cache) ----------
    def __serve_pool_signals__(self) -> Optional[Dict[str, Any]]:
        """Per-pool autoscaling signals (queued prefill tokens / decode
        lane occupancy) published by the replica's report loop."""
        if self.engine is None:
            return None
        return self.engine.pool_signals()

    def __serve_kv_inventory__(self) -> List[str]:
        """Digests of prompt prefixes whose KV blocks live in this
        replica's radix cache — the telemetry payload other replicas'
        InventoryViews read to resolve cluster prefix-cache owners."""
        if self.engine is None:
            return []
        return self.engine.kv_inventory()

    def export_prefix_kv(self, digest) -> Optional[Dict[str, Any]]:
        """Peer RPC: gather the cached prefix behind `digest` and put it
        on the object plane. Returns {"tokens", "ref" (hex),
        "n_data_blocks", "block_size"} or None when the prefix was
        evicted since it was advertised. The ObjectRef is pinned in a
        bounded deque so the payload survives until the peer fetches it
        (ring eviction after 64 exports is a re-fetchable miss, not a
        correctness problem — the peer just sees a get timeout and skips
        the import)."""
        if self.engine is None:
            return None
        d = self.engine.export_prefix(digest)
        if d is None:
            return None
        if self._export_refs is None:
            from collections import deque

            self._export_refs = deque(maxlen=64)
        self._export_refs.append(d.pop("_ref"))
        return d

    def _decode_handle(self):
        """Lazy handle back into THIS deployment, pinned to the decode
        pool — the migration pump resubmits finished prefills through it
        so decode-replica death reuses the handle's classify/redispatch
        machinery instead of growing a second failure path."""
        if self._decode_h is None:
            from ray_tpu.serve._internal import kv_plane
            from ray_tpu.serve.handle import DeploymentHandle

            ctx = kv_plane.current_replica_context()
            if not ctx:
                raise RuntimeError(
                    "prefill replica has no serve context; cannot route "
                    "to the decode pool")
            h = DeploymentHandle(ctx["deployment"], ctx["app"])
            h._pool = "decode"
            self._decode_h = h
        return self._decode_h

    def _resume_body(self, req, rid) -> Dict[str, Any]:
        from ray_tpu.serve._internal import kv_plane

        exp = req.export
        return kv_plane.make_resume_body(
            prompt=req.prompt, first_token=req.tokens[0],
            max_new_tokens=req.max_new_tokens, sampling=req.sampling,
            ref_hex=exp["ref_hex"], n_data_blocks=exp["n_data_blocks"],
            block_size=exp["block_size"], rid=rid,
            t_export=exp["t_export"])

    def _chain_decode(self, req, rid) -> List[int]:
        """Synchronous second hop: ship the migrated request's resume
        body to the decode pool and wait for the full token list. Holds
        `req` (and so the exported ObjectRef) alive until the decode
        side replied — the put must outlive the peer's get."""
        resp = self._decode_handle().remote(self._resume_body(req, rid))
        try:
            return resp.result(timeout=120.0)
        finally:
            del req  # release the KV payload ref only after the reply

    def _pump_migration(self, req, rid, deferred) -> None:
        """Deferred-path second hop, off the engine loop thread: the
        handle call blocks on the decode pool, so it runs on the pump
        executor and completes the caller's deferred when decode
        finishes (or fails it with the typed error so the CALLER's
        handle can classify — by then the prefill output already
        escaped, so only the decode hop is retried, internally)."""
        if self._pump is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pump = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="kv-migrate")

        def _run():
            try:
                deferred.complete(self._chain_decode(req, rid))
            except Exception as e:
                deferred.fail(e)

        self._pump.submit(_run)

    def _maybe_prefetch_prefix(self, prompt: List[int],
                               rid: Optional[str] = None) -> None:
        """Cluster prefix-cache read path: ONE digest + ONE inventory
        probe per request (lint-pinned). If another replica advertises
        this prompt's prefix and it is not cached locally, fetch its KV
        blocks over the object plane and graft them into the local radix
        cache BEFORE submit, so admission's ordinary lookup() hits.
        Strictly best-effort: every failure path degrades to a local
        prefill, and a per-digest memo rate-limits repeat attempts."""
        import time as _time

        from ray_tpu.serve._internal import kv_plane

        eng = self.engine
        if eng is None or not getattr(eng, "_cluster_cache", False):
            return
        if self.pool == "decode" or len(prompt) < self._digest_prefix_len:
            return
        dig = kv_plane.prefix_digest(prompt, self._digest_prefix_len)
        if eng.has_local_prefix(dig):
            return
        owner = kv_plane.InventoryView.instance().owner_of(dig)
        if rid:
            # the probe's lifeline record: STILL one dict probe per
            # request — the event is per-request bookkeeping, not a
            # second lookup
            try:
                from ray_tpu.observability import lifeline

                lifeline.record(rid, "inventory_probe",
                                owner=owner or "", hit=owner is not None)
            except Exception:
                pass
        if owner is None or owner == kv_plane.current_replica_name():
            return
        now = _time.monotonic()
        last = self._prefetch_memo.get(str(dig))
        if last is not None and now - last < 5.0:
            return
        if len(self._prefetch_memo) > 512:
            self._prefetch_memo.clear()
        self._prefetch_memo[str(dig)] = now
        try:
            import ray_tpu

            peer = ray_tpu.get_actor(owner)
            exp = ray_tpu.get(
                peer.handle_request.remote("export_prefix_kv", (dig,), {}),
                timeout=10.0)
            if not exp:
                return
            payload = kv_plane.fetch_kv_payload(exp["ref"], timeout=10.0)
            eng.import_prefix(exp["tokens"], payload["k"], payload["v"],
                              exp["n_data_blocks"])
            if rid:
                from ray_tpu.observability import lifeline

                lifeline.record(rid, "prefix_import", owner=owner,
                                blocks=int(exp["n_data_blocks"]))
        except Exception:
            pass  # cluster cache is an optimization, never a failure

    @batch(max_batch_size=32, batch_wait_timeout_s=0.02)
    def _generate(self, prompts: List[List[int]]) -> List[List[int]]:
        from ray_tpu.models import llama_decode

        # group by prompt length: each group is one static-shape
        # prefill + one device-side decode scan
        groups: Dict[int, List[int]] = {}
        for i, p in enumerate(prompts):
            groups.setdefault(len(p), []).append(i)
        out: List[Any] = [None] * len(prompts)
        for length, idxs in groups.items():
            arr = np.asarray([prompts[i] for i in idxs], np.int32)
            toks = llama_decode.generate(
                self.params, arr, self.cfg, max_new_tokens=self.max_new_tokens
            )
            for row, i in enumerate(idxs):
                out[i] = toks[row].tolist()
        return out

    def _call_resume(self, body) -> Optional[List[int]]:
        """Decode-pool entry for a migrated request: ONE object-plane
        get resolves the prefill side's KV payload, then the request
        resumes mid-stream via submit_resumed (no admission control —
        the prefill pool already admitted it; shedding here would lose
        a request whose first token was already produced)."""
        from ray_tpu.serve._internal import kv_plane
        from ray_tpu.experimental.direct_transport import maybe_defer

        if self.engine is None:
            raise ValueError("__kv_resume__ requires the continuous engine")
        payload = kv_plane.fetch_kv_payload(body["ref"],
                                            rid=body.get("rid"))
        sampling = SamplingParams.from_request(body.get("sampling"))
        kw = dict(
            prompt=[int(t) for t in body["prompt"]],
            first_token=int(body["first"]),
            max_new_tokens=int(body["max_new_tokens"]),
            k=payload["k"], v=payload["v"],
            n_data_blocks=int(body["n_data_blocks"]),
            sampling=sampling, rid=body.get("rid"),
            t_export=body.get("t_export"),
        )
        deferred = maybe_defer()
        if deferred is not None:
            def _complete(req):
                if req.error is None:
                    deferred.complete(req.tokens)
                else:
                    deferred.fail(req.exc or RuntimeError(
                        f"generation failed: {req.error}"))

            self.engine.submit_resumed(on_done=_complete, **kw)
            return None
        req = self.engine.submit_resumed(**kw)
        if not req.done.wait(120.0):
            self.engine.cancel(req, "cancelled: resume timed out")
            raise TimeoutError("resumed generation timed out")
        if req.error is not None:
            raise req.exc or RuntimeError(f"generation failed: {req.error}")
        return req.tokens

    def __call__(self, request) -> Optional[List[int]]:
        from ray_tpu.serve._internal import kv_plane

        if kv_plane.is_resume_body(request):
            return self._call_resume(request)
        if self.engine is not None:
            prompt, max_new, sampling, rid = _parse_request(
                request, self.max_new_tokens
            )
            from ray_tpu.experimental.direct_transport import maybe_defer

            self._maybe_prefetch_prefix(prompt, rid=rid)
            deferred = maybe_defer()
            if deferred is not None:
                # direct-transport fast path: submit() enqueues onto the
                # engine loop and the completion notification rides the
                # reply ring FROM the engine loop thread — no replica
                # thread parks on the done event and the completion costs
                # one ring write instead of an object-store round trip
                def _complete(req):
                    if req.error is not None:
                        # typed failure when the engine recorded one
                        # (shed / deadline / replica-death) — the class
                        # crosses the ring pickled, so the handle's
                        # redispatch policy classifies by isinstance
                        deferred.fail(req.exc or RuntimeError(
                            f"generation failed: {req.error}"))
                    elif req.finish_reason == "migrated":
                        # prefill pool: the prompt pass is done and the
                        # KV payload is on the object plane — hand off
                        # to the decode pool off-loop; the caller's
                        # deferred completes when decode finishes
                        self._pump_migration(req, rid, deferred)
                    else:
                        deferred.complete(req.tokens)

                # a submit() raise (dead engine, shed, bad request)
                # propagates: the transport surfaces it and disarms the
                # deferred
                self.engine.submit(
                    prompt, max_new, on_done=_complete, sampling=sampling,
                    rid=rid,
                )
                return None
            req = self.engine.submit(prompt, max_new, sampling=sampling,
                                     rid=rid)
            if not req.done.wait(120.0):
                self.engine.cancel(req, "cancelled: generation timed out")
                raise TimeoutError(
                    "generation timed out (request cancelled)")
            if req.error is not None:
                raise req.exc or RuntimeError(
                    f"generation failed: {req.error}")
            if req.finish_reason == "migrated":
                return self._chain_decode(req, rid)
            return req.tokens
        if isinstance(request, dict):
            raise ValueError(
                "per-request sampling needs the continuous engine "
                "(llm_deployment(continuous=True))"
            )
        return self._generate([int(t) for t in request])


def llm_deployment(num_replicas: int = 1, max_new_tokens: int = 32,
                   cfg=None, checkpoint_dir: Optional[str] = None,
                   continuous: bool = False, n_slots: int = 8,
                   chunk: int = 8, macro_phases: int = 8,
                   paged: Optional[bool] = None, block_size: int = 16,
                   n_blocks: int = 0, prefix_cache: bool = True,
                   max_queue: Optional[int] = None, draft_model=None,
                   num_speculative_tokens: int = 0,
                   pools: Optional[Dict[str, int]] = None,
                   cluster_cache: Optional[bool] = None,
                   digest_prefix_len: int = 32,
                   **deploy_kw):
    """A ready-to-run LLM generation application:

        app = llm_deployment(num_replicas=2, max_new_tokens=16)
        handle = serve.run(app, name="llm")
        handle.remote([1, 2, 3]).result()

    With continuous=True the replica runs the paged continuous-batching
    engine: requests may be dicts carrying SamplingParams fields
    (temperature/top_k/top_p/seed/stop/max_new_tokens, plus the
    relative `deadline_s` budget); `block_size` / `n_blocks` size the
    paged KV pool, `prefix_cache` toggles radix prompt-prefix reuse and
    `max_queue` bounds admission (excess requests shed with a typed
    retryable error instead of queueing unboundedly).

    `draft_model` + `num_speculative_tokens` turn on LOSSLESS
    draft-model speculative decoding (paged engine only): a small draft
    model proposes num_speculative_tokens tokens per lane each round
    and the target verifies them all in one batched dispatch, emitting
    every accepted token plus one correction/bonus token. Greedy output
    is bit-identical to non-speculative decoding and sampled output
    draws from the exact same distribution — the knob trades draft
    FLOPs for fewer target dispatches, it never changes results.
    `draft_model` accepts "self" (the target drafts for itself — only
    useful for testing), "self:N" (self-speculative truncation: the
    target's own first N layers draft, zero extra weights), a
    LlamaConfig (random init), or a dict of
    {"cfg": LlamaConfig, "checkpoint_dir"/"params"/"seed": ...}. With
    draft_model=None the replica compiles a program with zero draft
    FLOPs — speculation off costs nothing.

    `pools={"prefill": P, "decode": D}` turns on DISAGGREGATED serving
    (continuous paged engine only): the deployment runs P prefill
    replicas (admission + prompt pass, compute-bound) and D decode
    replicas (the token loop, bandwidth-bound); finished prefills ship
    their KV blocks to a decode replica over the object plane and the
    request resumes mid-stream there. With pools set, `num_replicas` is
    ignored (the pool counts ARE the replica counts) and per-pool
    autoscaling targets can ride autoscaling_config={"pools": {...}}.
    `cluster_cache` (default: on, kill switch
    RAY_TPU_SERVE_CLUSTER_CACHE=0) makes the radix prefix cache
    cluster-wide: replicas advertise committed prefix digests through
    telemetry, the router prefers the owning replica, and misses fetch
    the owner's KV blocks instead of re-prefilling;
    `digest_prefix_len` is the token window the cluster cache keys on.

    Generation is side-effect-free, so the deployment opts into
    replica-death REDISPATCH by default: a request in flight on a
    SIGKILLed/wedged replica (from which no output can have escaped —
    results deliver only at completion) is requeued onto a survivor by
    the handle; pass fault_config={"redispatch": False} to disable."""
    deploy_kw.setdefault("fault_config", {"redispatch": True})
    if pools is not None:
        if not continuous:
            raise ValueError(
                "pools= requires continuous=True (disaggregated serving "
                "runs on the continuous paged engine)")
        if paged is False or macro_phases <= 0:
            raise ValueError(
                "pools= requires the paged macro-step engine "
                "(macro_phases > 0 and paged != False)")
        deploy_kw["pool_config"] = dict(pools)
    dep = deployment(
        _LLMServer, name="LLMServer", num_replicas=num_replicas, **deploy_kw
    )
    return dep.bind(cfg=cfg, max_new_tokens=max_new_tokens,
                    checkpoint_dir=checkpoint_dir, continuous=continuous,
                    n_slots=n_slots, chunk=chunk, macro_phases=macro_phases,
                    paged=paged, block_size=block_size, n_blocks=n_blocks,
                    prefix_cache=prefix_cache, max_queue=max_queue,
                    draft_model=draft_model,
                    num_speculative_tokens=num_speculative_tokens,
                    cluster_cache=cluster_cache,
                    digest_prefix_len=digest_prefix_len)
