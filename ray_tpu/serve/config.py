"""Declarative Serve application config.

Equivalent of the reference's app-config deploy path (reference:
serve/schema.py ServeDeploySchema + ServeControllerClient
.deploy_application, serve/_private/client.py:284 — apps described as
data, built from an import path, deployment fields overridden from the
config, redeployed in place with a rolling replica swap).

Config shape (dict, or YAML text/file path)::

    applications:
      - name: app1                    # serve app name
        route_prefix: /app1
        import_path: my_module:app    # module:attr -> bound Application
        deployments:                  # optional per-deployment overrides
          - name: Model
            num_replicas: 2           # ignored once autoscaling is on
            ray_actor_options: {num_cpus: 1}
            # traffic-driven autoscaling (consumed by the controller's
            # control loop; validated at deployment() time — see
            # serve/_internal/autoscaler.py for every knob):
            autoscaling_config:
              min_replicas: 1
              max_replicas: 4
              target_ongoing_requests: 2
              upscale_delay_s: 2.0
              downscale_delay_s: 8.0
            # cache-affinity routing (prompt-prefix / session_id
            # consistent hashing with spill-to-least-loaded):
            affinity_config: {prefix_len: 32, spill_threshold: 8}
            # failure semantics: auto-requeue a dead replica's
            # in-flight requests onto survivors (side-effect-free
            # deployments only — see serve/errors.py):
            fault_config: {redispatch: true, max_redispatches: 1}
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional, Union

from ray_tpu.serve.api import Application, run as _run


def _load_config(config: Union[dict, str]) -> dict:
    if isinstance(config, dict):
        return config
    import os

    text = config
    if os.path.exists(config):
        with open(config) as f:
            text = f.read()
    import yaml

    return yaml.safe_load(text)


def _import_app(import_path: str) -> Application:
    mod_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(f"import_path must be 'module:attr', got {import_path!r}")
    mod = importlib.import_module(mod_name)
    app = getattr(mod, attr)
    if callable(app) and not isinstance(app, Application):
        app = app()  # app builder function
    if not isinstance(app, Application):
        raise TypeError(f"{import_path} resolved to {type(app).__name__}, not a bound Application")
    return app


def _apply_overrides(app: Application, overrides: List[Dict[str, Any]]) -> Application:
    """Rebuild the graph with per-deployment option overrides applied
    (options() returns a new Deployment; the graph is rebound bottom-up)."""
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"} for o in overrides}

    def rebind(node: Application) -> Application:
        def conv(v):
            return rebind(v) if isinstance(v, Application) else v

        args = tuple(conv(a) for a in node.init_args)
        kwargs = {k: conv(v) for k, v in node.init_kwargs.items()}
        dep = node.deployment
        ov = by_name.get(dep.name)
        if ov:
            dep = dep.options(**ov)
        return Application(dep, args, kwargs)

    return rebind(app)


def build_app(app_config: Dict[str, Any]) -> Application:
    """One application entry -> a bound (possibly overridden) Application."""
    app = _import_app(app_config["import_path"])
    if app_config.get("deployments"):
        app = _apply_overrides(app, app_config["deployments"])
    return app


def deploy_config(config: Union[dict, str]) -> Dict[str, Any]:
    """Deploy every application in the config; re-deploying an existing
    app name performs an in-place versioned upgrade (new replicas start
    and publish before old ones drain — no dropped requests)."""
    cfg = _load_config(config)
    handles = {}
    for app_cfg in cfg.get("applications", []):
        name = app_cfg.get("name", "default")
        app = build_app(app_cfg)
        handles[name] = _run(
            app, name=name, route_prefix=app_cfg.get("route_prefix", "/")
        )
    return handles
