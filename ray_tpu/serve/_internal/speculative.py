"""Draft-model resolution + reference acceptance math for speculative
decoding.

The device-side implementation (models/llama_decode.py:
spec_round_slots_paged) is the hot path; this module holds the two
host-side pieces the engine and the tests need:

- resolve_draft_model(): coerce the deployment-facing `draft_model`
  knob (None | "self" | LlamaConfig | dict) into (draft_params,
  draft_cfg) and validate the one geometry the acceptance rule
  REQUIRES the two models to share — the vocabulary. Everything else
  (depth, width, heads) is free: the draft runs its own paged KV pool
  sized from its own config, addressed through the target's block
  tables.
- numpy reference implementations of the lossless acceptance rule
  (greedy prefix-match and the Leviathan et al. 2023 residual/rejection
  construction), small enough to verify by eye — the tests cross-check
  the jitted kernel against these.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np


def resolve_draft_model(draft_model: Any, params, cfg) -> Tuple[Any, Any]:
    """Coerce the `draft_model` knob into (draft_params, draft_cfg).

    Accepted forms:
      - None          -> (None, None): speculation off.
      - "self"        -> the target drafts for itself (params shared,
                         zero extra weights). Every greedy proposal
                         matches the target argmax by construction, so
                         this is the acceptance-rate ceiling — the
                         test/bench harness configuration.
      - "self:N"      -> SELF-SPECULATIVE layer truncation (Zhang et
                         al. 2023, "Draft & Verify"): the draft is the
                         target's own first N transformer layers with
                         the shared embed/final_norm/lm_head — zero
                         extra weights, draft passes ~N/n_layers the
                         cost, and acceptance degrades gracefully with
                         the truncation depth while staying LOSSLESS
                         (the rule never depends on draft quality).
      - LlamaConfig   -> fresh random init from seed 0 (tests).
      - dict          -> {"cfg": LlamaConfig, and one of
                         "params": pytree | "checkpoint_dir": str |
                         "seed": int (random init, default 0)}.

    Raises ValueError when the draft vocabulary differs from the
    target's: acceptance compares the two distributions token-by-token,
    so a vocab mismatch is a config error, not a degraded mode.
    """
    if draft_model is None:
        return None, None
    if isinstance(draft_model, str):
        if draft_model == "self":
            return params, cfg
        if draft_model.startswith("self:"):
            import dataclasses

            import jax

            try:
                n = int(draft_model.split(":", 1)[1])
            except ValueError:
                n = 0
            if not 1 <= n <= cfg.n_layers:
                raise ValueError(
                    f"'self:N' draft needs 1 <= N <= n_layers "
                    f"({cfg.n_layers}), got {draft_model!r}"
                )
            draft_cfg = dataclasses.replace(cfg, n_layers=n)
            # layers are scan-stacked (leading dim n_layers): the first
            # N slices ARE the truncated draft, views over the target's
            # own weights — no copy, no extra memory
            draft_params = dict(params)
            draft_params["layers"] = jax.tree_util.tree_map(
                lambda a: a[:n], params["layers"])
            return draft_params, draft_cfg
        raise ValueError(
            f"string draft_model must be 'self' or 'self:N', "
            f"got {draft_model!r}"
        )
    from ray_tpu.models import llama

    seed = 0
    if isinstance(draft_model, llama.LlamaConfig):
        draft_cfg = draft_model
        draft_params = None
    elif isinstance(draft_model, dict):
        body = dict(draft_model)
        draft_cfg = body.pop("cfg", None)
        if not isinstance(draft_cfg, llama.LlamaConfig):
            raise ValueError(
                "dict draft_model must carry a 'cfg' LlamaConfig "
                f"(got {type(draft_cfg).__name__})"
            )
        draft_params = body.pop("params", None)
        ckpt = body.pop("checkpoint_dir", None)
        seed = int(body.pop("seed", 0))
        if body:
            raise ValueError(f"unknown draft_model field(s) {sorted(body)}")
        if draft_params is None and ckpt is not None:
            from ray_tpu.train.orbax_utils import load_pytree_from_checkpoint

            draft_params = load_pytree_from_checkpoint(ckpt)
    else:
        raise ValueError(
            "draft_model must be None, 'self', a LlamaConfig, or a dict "
            f"(got {type(draft_model).__name__})"
        )
    if draft_cfg.vocab_size != cfg.vocab_size:
        raise ValueError(
            f"draft vocab_size {draft_cfg.vocab_size} != target "
            f"{cfg.vocab_size}: lossless acceptance compares the two "
            "distributions over one shared vocabulary"
        )
    if draft_params is None:
        import jax

        draft_params = llama.init_params(jax.random.PRNGKey(seed), draft_cfg)
    return draft_params, draft_cfg


# ---------------------------------------------------------------- reference
# numpy mirrors of the device acceptance rule, used by the tests to
# cross-check the jitted kernel. Shapes: draft (S,) proposed tokens,
# target_argmax (S+1,) per-position target argmax, p/q (V,) warped
# probability rows.


def greedy_accept_len(draft: np.ndarray, target_argmax: np.ndarray) -> int:
    """Length of the accepted prefix under the greedy rule: the longest
    prefix where every draft token equals the target argmax at its
    position. The emitted correction/bonus is target_argmax[n]."""
    n = 0
    for j in range(len(draft)):
        if int(draft[j]) != int(target_argmax[j]):
            break
        n += 1
    return n


def accept_token(p_d: float, q_d: float, u: float) -> bool:
    """One rejection-sampling acceptance test: keep the draft token
    with probability min(1, p(d)/q(d)) given uniform u in [0, 1)."""
    return u * max(q_d, 1e-20) < p_d


def residual_distribution(p: np.ndarray, q: np.ndarray) -> np.ndarray:
    """The rejection-case distribution normalize(max(p - q, 0)). Sampling
    the correction token from it makes (accepted prefix + correction)
    an EXACT sample from the target distribution p — Leviathan et al.
    2023, Theorem 1. At the bonus position q := 0, so this degrades to
    p itself (a pure target sample)."""
    r = np.maximum(np.asarray(p, np.float64) - np.asarray(q, np.float64), 0.0)
    s = r.sum()
    if s <= 0.0:  # p == q exactly: residual mass underflows, fall back to p
        return np.asarray(p, np.float64) / max(np.asarray(p).sum(), 1e-20)
    return r / s


def expected_accept_prob(p: np.ndarray, q: np.ndarray) -> float:
    """Marginal acceptance probability of one draft position:
    sum_d q(d) * min(1, p(d)/q(d)) = 1 - 0.5 * ||p - q||_1. Useful for
    sizing num_speculative_tokens against a measured draft gap."""
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    return float(1.0 - 0.5 * np.abs(p - q).sum())
