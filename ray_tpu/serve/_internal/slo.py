"""Serve SLO plane: per-deployment objectives, attainment, burn rates.

Mooncake-style serving is operated on three numbers — TTFT, TPOT and
availability — so the deployment API takes them as a first-class
``slo_config`` and the controller folds the telemetry the engines
already publish (latency histograms' p99s, shed/deadline counters, the
health loop's lost-request ledger) into an operating signal:

- ATTAINMENT: is the measured p99 under the target right now, and by
  how much (headroom, signed — negative means the target is blown).
- AVAILABILITY + BURN RATE: availability counts a request as *bad*
  when it was shed, expired past its deadline, or was in flight on a
  replica that died. The burn rate is the SRE multi-window form:
  ``(bad / total) / (1 - availability_target)`` over a FAST window
  (default 60 s — pages) and a SLOW window (default 300 s — tickets).
  Burn 1.0 means the error budget is being spent exactly at the rate
  that exhausts it at the window's end; >> 1 means the deployment is
  on fire regardless of what the lifetime average still says.

Everything here is controller-side arithmetic over snapshots fetched
ONCE per control tick — the request hot paths never see this module.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

# burn-rate windows (seconds): fast (paging) and slow (ticketing)
BURN_WINDOWS_S = (60.0, 300.0)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Per-deployment serving objectives.

    ttft_p99_ms: target 99th-percentile time-to-first-token (ms).
    tpot_p99_ms: target 99th-percentile time-per-output-token (ms).
    availability: target fraction of requests NOT shed/expired/lost,
        e.g. 0.999. The error budget is ``1 - availability``.
    """

    ttft_p99_ms: Optional[float] = None
    tpot_p99_ms: Optional[float] = None
    availability: Optional[float] = None

    def __post_init__(self):
        for knob in ("ttft_p99_ms", "tpot_p99_ms"):
            v = getattr(self, knob)
            if v is not None and not v > 0:
                raise ValueError(
                    f"slo_config: {knob} must be > 0, got {v}")
        if self.availability is not None and not (
                0.0 < self.availability <= 1.0):
            raise ValueError(
                f"slo_config: availability must be in (0, 1], got "
                f"{self.availability}")
        if (self.ttft_p99_ms is None and self.tpot_p99_ms is None
                and self.availability is None):
            raise ValueError(
                "slo_config: at least one objective required "
                "(ttft_p99_ms / tpot_p99_ms / availability)")


_SLO_KEYS = tuple(f.name for f in dataclasses.fields(SloConfig))


def validate_slo_config(cfg: Optional[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """Validate a user slo_config dict at deployment() time."""
    if cfg is None:
        return None
    if not isinstance(cfg, dict):
        raise ValueError(
            f"slo_config must be a dict, got {type(cfg).__name__}")
    unknown = set(cfg) - set(_SLO_KEYS)
    if unknown:
        raise ValueError(
            f"slo_config: unknown key(s) {sorted(unknown)}; valid "
            f"keys: {sorted(_SLO_KEYS)}")
    return dataclasses.asdict(SloConfig(**cfg))


def _worst(vals):
    vals = [v for v in vals if v is not None]
    return max(vals) if vals else None


class SloState:
    """One deployment's SLO evaluator: feed it per-tick cumulative
    counters + current latency percentiles; read ``snapshot()``.

    The availability stream rides CUMULATIVE counters (completed /
    shed / lost since engine start), so the evaluator works from
    samples and window deltas — a missed tick loses resolution, never
    correctness. Replica churn can step counters backwards (a fresh
    engine restarts at zero); deltas clamp at 0 so a restart reads as
    "no new traffic", not negative traffic.
    """

    def __init__(self, cfg: Dict[str, Any],
                 windows_s: Tuple[float, ...] = BURN_WINDOWS_S):
        self.cfg = dict(cfg)
        self.windows_s = tuple(windows_s)
        # (t, good_cum, bad_cum) samples covering the longest window
        self._samples: Deque[Tuple[float, float, float]] = deque()
        self._last: Optional[Tuple[float, float]] = None  # (good, bad) cum
        self._good = 0.0   # monotonic, restart-proof accumulation
        self._bad = 0.0
        self._latest: Dict[str, Any] = {}

    # ------------------------------------------------------------ feeding
    def observe(self, good_cum: float, bad_cum: float,
                ttft_p99_ms: Optional[float] = None,
                tpot_p99_ms: Optional[float] = None,
                now: Optional[float] = None) -> None:
        """One evaluator tick. `good_cum`/`bad_cum` are the summed
        cumulative counters across the deployment's live engines plus
        the controller's lost-request ledger; percentiles are the worst
        (max) across replicas — an SLO is blown if ANY replica blows
        it."""
        if now is None:
            now = time.time()
        if self._last is not None:
            dg = max(0.0, good_cum - self._last[0])
            db = max(0.0, bad_cum - self._last[1])
        else:
            dg, db = max(0.0, good_cum), max(0.0, bad_cum)
        self._last = (good_cum, bad_cum)
        self._good += dg
        self._bad += db
        self._samples.append((now, self._good, self._bad))
        horizon = now - max(self.windows_s) - 5.0
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()
        self._latest = {"t": now, "ttft_p99_ms": ttft_p99_ms,
                        "tpot_p99_ms": tpot_p99_ms}

    # ------------------------------------------------------------ reading
    def _window_rate(self, window_s: float, now: float
                     ) -> Tuple[float, float]:
        """(good, bad) deltas over the trailing window."""
        if not self._samples:
            return 0.0, 0.0
        cutoff = now - window_s
        base = None
        for t, g, b in self._samples:
            if t >= cutoff:
                break
            base = (g, b)
        end = self._samples[-1]
        if base is None:
            base = (0.0, 0.0)
        return max(0.0, end[1] - base[0]), max(0.0, end[2] - base[1])

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The published ``slo:<app>::<dep>`` payload: per-objective
        target / observed / attained plus multi-window burn rates."""
        if now is None:
            now = time.time()
        out: Dict[str, Any] = {"config": dict(self.cfg), "time": now}
        lat = self._latest
        for key in ("ttft_p99_ms", "tpot_p99_ms"):
            target = self.cfg.get(key)
            if target is None:
                continue
            observed = lat.get(key)
            entry: Dict[str, Any] = {"target": target,
                                     "observed": observed}
            if observed is not None:
                entry["attained"] = bool(observed <= target)
                # signed headroom: +40 means the p99 is running at 60%
                # of target; negative means the target is blown by that %
                entry["headroom_pct"] = round(
                    100.0 * (target - observed) / target, 1)
            out[key] = entry
        target_av = self.cfg.get("availability")
        if target_av is not None:
            total = self._good + self._bad
            observed_av = (self._good / total) if total > 0 else None
            entry = {"target": target_av, "observed":
                     round(observed_av, 6) if observed_av is not None
                     else None,
                     "good": int(self._good), "bad": int(self._bad)}
            if observed_av is not None:
                entry["attained"] = bool(observed_av >= target_av)
            budget = max(1e-9, 1.0 - target_av)
            burn: Dict[str, Any] = {}
            for w in self.windows_s:
                g, b = self._window_rate(w, now)
                tot = g + b
                burn[f"{int(w)}s"] = round(
                    (b / tot) / budget, 3) if tot > 0 else 0.0
            entry["burn_rate"] = burn
            out["availability"] = entry
        atts = [v.get("attained") for k, v in out.items()
                if isinstance(v, dict) and "attained" in v]
        if atts:
            out["attained"] = bool(all(atts))
        return out


def fold_engine_metrics(engines: Dict[str, Dict[str, Any]],
                        lost_requests: int = 0) -> Dict[str, Any]:
    """Collapse the per-replica ``engine:<name>`` telemetry snapshots
    of ONE deployment into the evaluator's inputs: summed good/bad
    cumulative counters and worst-case p99s. `lost_requests` is the
    controller's ledger of requests in flight on replicas declared
    dead (the third bad-request source — engines can't count their own
    death)."""
    good = 0.0
    bad = float(lost_requests)
    ttfts, tpots = [], []
    for m in engines.values():
        if not isinstance(m, dict):
            continue
        good += float(m.get("requests_completed") or 0)
        bad += float(m.get("shed_requests")
                     or (m.get("shed_queue_full", 0)
                         + m.get("shed_eta", 0)))
        bad += float(m.get("deadline_expired") or 0)
        ttfts.append(m.get("ttft_ms_p99"))
        tpots.append(m.get("tpot_ms_p99"))
    return {"good": good, "bad": bad,
            "ttft_p99_ms": _worst(ttfts), "tpot_p99_ms": _worst(tpots)}
