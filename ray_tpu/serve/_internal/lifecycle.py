"""Replica crash/restart bookkeeping — backoff + crash-loop breaker.

The controller's health loop detects dead/wedged replicas; THIS module
decides when a replacement may start. Pure host logic with an explicit
``now`` everywhere (the AutoscalerState pattern), so unit tests replay
synthetic crash traces on a fake clock:

- exponential restart backoff: the Nth crash inside the sliding window
  delays the next restart by ``backoff_base_s * 2**(N-1)`` (capped) —
  a replica that dies on arrival must not be respawned at the control
  loop's full tick rate.
- crash-loop circuit breaker: ``threshold`` crashes inside ``window_s``
  OPEN the breaker — no restarts at all until ``cooldown_s`` passes,
  then ONE half-open probe restart is allowed; further refills wait
  until the probe survives ``window_s`` (the breaker closes) or it
  crashes (straight back to open). A deployment whose __init__
  segfaults gets pinned at "crash_looped" on /api/serve instead of
  eating the cluster with a fork bomb of doomed replicas.

State transitions happen ONLY in ``record_crash`` and ``restart_at``
(the gate the health loop consults before actually restarting);
``state()`` is a derived read — a dashboard poll can never advance the
breaker or mint events.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional


class CrashLoopBreaker:
    """One deployment's crash history + restart gate."""

    def __init__(self, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 window_s: float = 30.0, threshold: int = 5,
                 cooldown_s: float = 30.0):
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.window_s = window_s
        self.threshold = max(1, threshold)
        self.cooldown_s = cooldown_s
        self._crashes: deque = deque()   # crash timestamps (window-pruned)
        self._opened_at: Optional[float] = None
        self._probe_at: Optional[float] = None  # half-open probe launch time
        # replica state transitions, newest last (published on /api/serve)
        self.events: deque = deque(maxlen=32)

    # ------------------------------------------------------------ inputs
    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._crashes and self._crashes[0] < cutoff:
            self._crashes.popleft()

    def record_crash(self, replica: str, now: float, reason: str = "died") -> None:
        self._prune(now)
        self._crashes.append(now)
        self.events.append({"t": round(now, 3), "replica": replica,
                            "event": "died", "reason": reason})
        if self._probe_at is not None:
            # the half-open probe (or a survivor beside it) crashed:
            # straight back to open, cooldown restarts from this crash
            self._probe_at = None
            self._opened_at = now
            self.events.append({"t": round(now, 3), "replica": replica,
                                "event": "breaker_reopened"})
        elif self._opened_at is None and len(self._crashes) >= self.threshold:
            self._opened_at = now
            self.events.append({"t": round(now, 3), "replica": replica,
                                "event": "breaker_opened"})

    def record_restart(self, replica: str, now: float) -> None:
        self.events.append({"t": round(now, 3), "replica": replica,
                            "event": "restarted"})

    # ----------------------------------------------------------- queries
    def _phase(self, now: float) -> Optional[str]:
        """Derived breaker phase (no mutation): crash_looped inside the
        cooldown, half_open from cooldown expiry until the probe has
        survived its window, else None (closed)."""
        if self._opened_at is not None:
            if now - self._opened_at < self.cooldown_s:
                return "crash_looped"
            return "half_open"  # probe not yet taken (restart_at takes it)
        if self._probe_at is not None and now - self._probe_at < self.window_s:
            return "half_open"  # probe out, proving itself
        return None

    def _backoff_at(self, now: float) -> float:
        """Earliest backoff-gated restart time from the crash window
        (no mutation)."""
        if not self._crashes:
            return now
        n = len(self._crashes)
        delay = min(self.backoff_max_s, self.backoff_base_s * (2 ** (n - 1)))
        return self._crashes[-1] + delay

    def probing(self, now: float) -> bool:
        """True while the half-open probe must prove itself — the
        caller restarts AT MOST ONE replica in this state."""
        return self._phase(now) == "half_open"

    def restart_at(self, now: float) -> Optional[float]:
        """Earliest time a replacement replica may start: ``now`` when
        clear, a future time while backing off, None while the breaker
        is open (crash-looped) or a probe is already out. Consulting
        this during an expired cooldown TAKES the half-open probe slot
        (the caller is expected to restart one replica)."""
        self._prune(now)
        if self._opened_at is not None:
            if now - self._opened_at < self.cooldown_s:
                return None
            # cooldown expired: transition to half-open, hand out the
            # one probe slot
            self._opened_at = None
            self._probe_at = now
            self.events.append({"t": round(now, 3), "replica": None,
                                "event": "breaker_half_open"})
            return now
        if self._probe_at is not None:
            if now - self._probe_at < self.window_s:
                return None  # probe still proving itself: no refills
            # probe survived a full window: breaker closes
            self._probe_at = None
            self.events.append({"t": round(now, 3), "replica": None,
                                "event": "breaker_closed"})
        return self._backoff_at(now)

    def state(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Derived snapshot — never advances the breaker (a status poll
        must not take the probe slot or mint transition events)."""
        now = time.time() if now is None else now
        self._prune(now)
        st = self._phase(now)
        if st is None:
            st = "backing_off" if (
                self._crashes and now < self._backoff_at(now)
            ) else "healthy"
        return {
            "state": st,
            "recent_crashes": len(self._crashes),
            "events": list(self.events),
        }
