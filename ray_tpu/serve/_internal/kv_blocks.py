"""Block-table KV allocator: the host side of paged attention.

The device KV cache in paged mode is one global pool of fixed-size
blocks, (L, n_blocks, block_size, kvh, hd); a sequence owns a LIST of
block ids (its block table) instead of a contiguous (max_len,) stripe,
so slot count decouples from sequence length — the PagedAttention idea
(Kwon et al., SOSP '23), restated for static-shape TPU programs: block
tables never live on device, they ride every dispatch as i32 program
arguments exactly like prompt tokens do.

This module is the allocator over that pool. Pure host bookkeeping —
no jax imports, no device state:

- block 0 is the NULL block, never allocated: device programs direct
  every masked-off or inactive write at it (a finished slot's lanes, an
  admission row's right-padding), so garbage writes land somewhere
  harmless instead of corrupting a block that was freed and reused.
- blocks are REFCOUNTED: a block can be owned by a running request and
  simultaneously pinned by the radix prefix cache, or shared read-only
  by any number of requests that matched it as a prompt prefix. It
  returns to the free pool only when the last reference drops.
- COPY-ON-WRITE: fork() shares every block of an existing table
  (refcount bump, zero copies); ensure_writable() is the write barrier
  — called before appending into a block that turned out to be shared,
  it allocates a private replacement and reports the (src, dst) pair so
  the caller can issue the device-side block copy. The serve path only
  shares FULL prompt blocks (append positions never land inside them),
  so COW triggers there exactly never — it exists for fork()-style
  sequence splitting (beam/best-of) and is tested at that level.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

NULL_BLOCK = 0


class BlockPoolExhausted(RuntimeError):
    """alloc() could not be satisfied even after cache eviction."""


class BlockAllocator:
    """Refcounted allocator over `n_blocks` fixed-size KV blocks.

    Not thread-safe by design: the engine mutates it only from the
    planner (engine-loop) thread; metrics() reads integer snapshots,
    which are atomic under the GIL.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved null)")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = n_blocks
        self.block_size = block_size
        # LIFO free list: recently freed blocks are re-handed first, so a
        # churned pool keeps touching the same HBM region (cache-warm)
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._ref = [0] * n_blocks

    # ------------------------------------------------------------ alloc
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.n_blocks - 1) - len(self._free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(0, n_tokens) // self.block_size)

    def alloc(self, n: int) -> List[int]:
        """Take `n` blocks (refcount 1 each). Raises BlockPoolExhausted
        without side effects if fewer than n are free — admission
        planning relies on all-or-nothing."""
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} KV blocks, {len(self._free)} free "
                f"(pool {self.n_blocks - 1})"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b == NULL_BLOCK:
                continue
            if self._ref[b] <= 0:
                raise ValueError(f"incref on free block {b}")
            self._ref[b] += 1

    def decref(self, blocks: Sequence[int]) -> List[int]:
        """Drop one reference per block; returns the blocks that reached
        zero and went back to the pool."""
        freed = []
        for b in blocks:
            if b == NULL_BLOCK:
                continue
            if self._ref[b] <= 0:
                raise ValueError(f"decref on free block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)
                freed.append(b)
        return freed

    def refcount(self, block: int) -> int:
        return self._ref[block]

    # -------------------------------------------------------------- COW
    def fork(self, table: Sequence[int]) -> List[int]:
        """Share an existing table: every block's refcount bumps, no
        copies. The fork must go through ensure_writable() before any
        in-place append."""
        blocks = [b for b in table if b != NULL_BLOCK]
        self.incref(blocks)
        return list(blocks)

    def ensure_writable(self, table: List[int], index: int
                        ) -> Optional[Tuple[int, int]]:
        """Copy-on-write barrier: make table[index] exclusively owned.
        If the block is shared (refcount > 1), allocate a replacement,
        swap it into the table, drop the shared reference, and return
        (src, dst) so the caller can issue the device block copy.
        Returns None when the block was already exclusive."""
        b = table[index]
        if b == NULL_BLOCK:
            raise ValueError("ensure_writable on the null block")
        if self._ref[b] == 1:
            return None
        dst = self.alloc(1)[0]
        table[index] = dst
        self.decref([b])
        return (b, dst)

    # ------------------------------------------------------------ audit
    def leaked(self) -> Dict[int, int]:
        """block -> refcount for every non-free block. Empty dict ==
        every reference was returned (the CI block-leak audit)."""
        return {b: r for b, r in enumerate(self._ref) if b != NULL_BLOCK and r > 0}

    def check_zero(self) -> bool:
        return not self.leaked()
