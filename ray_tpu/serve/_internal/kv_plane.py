"""Cluster-wide KV plane: prefill→decode migration + prefix inventory.

Disaggregated serving (DistServe, Zhong et al.; Mooncake, Qin et al.)
splits one logical LLM deployment into two replica pools with opposite
resource profiles: PREFILL replicas run admission + prompt prefill only
(compute-bound, bursty), DECODE replicas run the token loop
(memory-bandwidth-bound, steady). The seam between them is KV state,
and this module is that seam:

- MIGRATION: a prefill replica finishes a request's prompt pass (one
  macro-step admission that samples the first token), lifts the
  request's KV blocks out of the paged pool as ONE pair of device
  arrays (models/llama_decode.gather_kv_blocks), and ships them through
  the PR-12 zero-copy object plane with ONE put per handoff —
  never per-block serialization. The decode replica fetches with ONE
  get (dlpack, zero-copy on colocated hosts), scatters the slices into
  its own pool (import_kv_blocks), and the request resumes mid-stream
  in the paged macro-step engine with its first token, position,
  remaining budget and rng key intact. Sampled streams stay
  reproducible across the hop because the carried rng key is a pure
  function of the request seed (carried_rng_for_seed mirrors
  admit_slots_paged's split), not device state that would have to ride
  the payload.
- FAILURE SEMANTICS: the prefill replica holds the exported ObjectRef
  until the decode replica's reply lands, so a decode replica SIGKILLed
  mid-handoff surfaces as a typed ReplicaDiedError(started=False) at
  the internal handle — no output escaped (results deliver only at
  completion), the resume body redispatches to a surviving decode
  replica, and the payload is still fetchable from the exporter-owned
  object store.
- CLUSTER-WIDE PREFIX CACHE: every engine registers the digests of the
  prompt prefixes its radix cache committed; the Replica stat reporter
  publishes that inventory through the PR-4 telemetry path, and the
  process-wide InventoryView polls the merged table so (a) the PR-8
  affinity router can consult the inventory BEFORE consistent-hashing
  (a prefix prefilled anywhere routes its repeat traffic to the replica
  that owns it) and (b) a replica that misses locally can fetch the
  committed blocks from the owner (export→put→get→scatter, the same
  one-put/one-get discipline) instead of re-prefilling them. The digest
  is bit-identical to the handle's affinity digest, so the router's key
  IS the inventory key.

Everything here is host-side policy over PR-7 primitives: the pool
stays (L, n_blocks, bs, kvh, hd), block 0 stays the garbage-safe null
block (bucket padding aims at it on both ends of the wire), and
allocator/trie mutation stays on the engine loop thread.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

NULL_BLOCK = 0

# replica-name context: ONE serve replica actor lives per worker
# process, so the controller's Replica wrapper records its actor name
# here before constructing the user instance — the LLM server reads it
# back to learn its own (app, deployment, replica) coordinates without
# threading them through user init kwargs
_replica_name: List[Optional[str]] = [None]


def set_replica_name(name: Optional[str]) -> None:
    _replica_name[0] = name


def current_replica_name() -> Optional[str]:
    return _replica_name[0]


def current_replica_context() -> Dict[str, str]:
    """Parse this process's ``SERVE_REPLICA::<app>::<dep>::<n>`` actor
    name into {replica, app, deployment}; {} outside a replica."""
    name = _replica_name[0]
    if not name:
        return {}
    parts = name.split("::")
    if len(parts) < 4 or parts[0] != "SERVE_REPLICA":
        return {}
    return {"replica": name, "app": parts[1], "deployment": parts[2]}


def cluster_cache_enabled(knob: Optional[bool]) -> bool:
    """Resolve the cluster-cache kill switch: an explicit deployment
    knob wins; otherwise the RAY_TPU_SERVE_CLUSTER_CACHE env var
    (default on). The off state must cost zero RPCs — callers gate
    every inventory/fetch path on this."""
    if knob is not None:
        return bool(knob)
    return os.environ.get("RAY_TPU_SERVE_CLUSTER_CACHE", "1") not in (
        "0", "false", "off")


# ------------------------------------------------------------- digests
def prefix_digest(tokens: Sequence[int], prefix_len: int) -> int:
    """The cluster cache key for a prompt prefix — BIT-IDENTICAL to the
    handle's affinity digest (serve/handle.py _affinity_digest), so the
    router's hash key doubles as the inventory lookup key with zero
    extra hashing."""
    data = b" ".join(str(int(t)).encode() for t in tokens[:prefix_len])
    return int.from_bytes(hashlib.md5(data).digest()[:8], "big")


def carried_rng_for_seed(seed: int):
    """Host-side recompute of the rng key a sampled slot carries after
    admission: admit_slots_paged seeds PRNGKey(seed), splits once, uses
    pair[1] for the first token and stores pair[0] ("carried") in the
    slot. Recomputing it from the seed is exact — so a migration never
    ships device rng state (which could already belong to a reused
    slot by export time)."""
    import jax
    import numpy as np

    key = jax.random.PRNGKey(np.uint32(seed & 0xFFFFFFFF))
    carried = jax.random.split(key)[0]
    return np.asarray(carried, np.uint32)


# ---------------------------------------------------------- block wire
def pad_block_ids(blocks: Sequence[int]) -> "Any":
    """Bucket block-id lists to powers of two (null-block padded) so
    the gather/scatter jit variants stay bounded: exporter and importer
    call the same function, so the shipped array shape always matches
    the importer's scatter plan."""
    import numpy as np

    n = max(1, len(blocks))
    b = 1
    while b < n:
        b *= 2
    out = np.full(b, NULL_BLOCK, np.int32)
    out[: len(blocks)] = blocks
    return out


def export_kv_blocks(cache: Dict[str, Any], blocks: Sequence[int],
                     rid: Optional[str] = None):
    """Lift `blocks` out of a paged pool and publish them to the object
    plane. ONE fused gather dispatch + ONE ray_tpu.put per call — the
    migration hot path's pinned cost (tests/test_lint_kv_plane.py).
    Returns (ObjectRef, padded_width). The put serializes via the
    dlpack path, which synchronizes on the gather's result, so callers
    may free the source blocks the moment this returns. `rid` stamps a
    ``kv_put`` event on the request's lifeline (per-handoff, never
    per-block — the lint budget is unchanged)."""
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu.models import llama_decode as D

    ids = pad_block_ids(blocks)
    k, v = D.jitted_gather_kv_blocks()(cache, jnp.asarray(ids))
    ref = ray_tpu.put({"k": k, "v": v, "n": len(blocks)})
    if rid:
        try:
            from ray_tpu.observability import lifeline

            lifeline.record(rid, "kv_put", blocks=len(blocks),
                            ref=ref.hex()[:16], a=float(len(blocks)))
        except Exception:
            pass
    return ref, len(ids)


def fetch_kv_payload(ref_hex: str, timeout: float = 30.0,
                     rid: Optional[str] = None) -> Dict[str, Any]:
    """The import side's ONE object-plane get: resolve the exporter's
    ref (hex form — refs ride request bodies as strings) into the
    {"k", "v", "n"} payload of device arrays. `rid` stamps a
    ``resume_fetch`` event on the request's lifeline."""
    import ray_tpu
    from ray_tpu._private.object_ref import ObjectRef

    t0 = time.perf_counter()
    payload = ray_tpu.get(ObjectRef(bytes.fromhex(ref_hex)), timeout=timeout)
    if rid:
        try:
            from ray_tpu.observability import lifeline

            lifeline.record(rid, "resume_fetch", ref=ref_hex[:16],
                            fetch_ms=round(
                                (time.perf_counter() - t0) * 1e3, 3),
                            a=(time.perf_counter() - t0) * 1e3)
        except Exception:
            pass
    return payload


# ---------------------------------------------------------- resume body
def make_resume_body(prompt: Sequence[int], first_token: int,
                     max_new_tokens: int, sampling, ref_hex: str,
                     n_data_blocks: int, block_size: int,
                     rid: Optional[str] = None,
                     t_export: Optional[float] = None) -> Dict[str, Any]:
    """The migration handoff request: a plain dict the decode replica's
    __call__ recognizes by the __kv_resume__ marker. `prompt` rides at
    the top level so the internal handle's affinity digest (and thus
    the decode pool's cache-affinity routing) works unchanged on resume
    bodies."""
    import dataclasses

    return {
        "__kv_resume__": True,
        "ref": ref_hex,
        "prompt": [int(t) for t in prompt],
        "first": int(first_token),
        "max_new_tokens": int(max_new_tokens),
        "sampling": dataclasses.asdict(sampling),
        "n_data_blocks": int(n_data_blocks),
        "block_size": int(block_size),
        "rid": rid,
        "t_export": t_export,
    }


def is_resume_body(request) -> bool:
    return isinstance(request, dict) and bool(request.get("__kv_resume__"))


# ------------------------------------------------------------ inventory
class InventoryView:
    """Process-wide read model of every replica's published block
    inventory (prefix digests), refreshed from the merged GCS `serve`
    telemetry table on a background thread. Consumers pay ONE dict
    probe per lookup (`owner_of`) — never an RPC on the request path;
    the refresher's single fetch_snapshots round trip per period is the
    entire cluster-wide cost, identical in shape to the controller's
    autoscaler feed.

    Staleness is bounded by the refresh period + the reporters' publish
    cadence (~0.5–2 s): a stale positive costs one failed fetch that
    falls back to a local prefill, a stale negative costs one re-route
    through the plain affinity ring — both safe."""

    _instance: Optional["InventoryView"] = None
    _instance_lock = threading.Lock()

    def __init__(self, period_s: float = 1.0):
        self.period_s = period_s
        self._owners: Dict[str, str] = {}   # str(digest) -> replica name
        self._pools: Dict[str, str] = {}    # replica name -> pool role
        self._t_refresh = 0.0
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "InventoryView":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _ensure_thread(self) -> None:
        if self._thread is not None:
            return
        with self._lock:
            if self._thread is None:
                t = threading.Thread(
                    target=self._poll_loop, daemon=True,
                    name="kv-plane-inventory")
                self._thread = t
                t.start()

    def _poll_loop(self) -> None:
        while True:
            try:
                self.refresh_now()
            except Exception:
                pass
            time.sleep(self.period_s)

    def refresh_now(self) -> None:
        """One merged-table fetch -> atomic swap of the lookup dicts
        (readers never take the lock: dict replacement is atomic)."""
        from ray_tpu.observability import fetch_snapshots

        owners: Dict[str, str] = {}
        pools: Dict[str, str] = {}
        for snap in fetch_snapshots("serve", timeout=2.0).values():
            if not isinstance(snap, dict):
                continue
            for key, val in snap.items():
                if (not isinstance(key, str)
                        or not key.startswith("replica:")
                        or not isinstance(val, dict)):
                    continue
                name = key[len("replica:"):]
                pool = val.get("pool")
                if pool:
                    pools[name] = pool
                for d in val.get("kv_inventory") or ():
                    # first writer wins per refresh; any owner works —
                    # the payload is the same prefix KV everywhere
                    owners.setdefault(str(d), name)
        self._owners = owners
        self._pools = pools
        self._t_refresh = time.monotonic()

    def owner_of(self, digest) -> Optional[str]:
        """Replica name owning `digest`'s prefix blocks — ONE dict
        probe (the request-path budget the lint test pins)."""
        self._ensure_thread()
        return self._owners.get(str(digest))

    def pool_of(self, replica_name: str) -> Optional[str]:
        return self._pools.get(replica_name)


# -------------------------------------------------- engine-side ledger
class PrefixInventory:
    """An engine's OWN registry of committed prefix digests: digest ->
    the exact committed token prefix (what a peer needs to walk this
    engine's radix trie for the export). Capped LRU; the publishable
    digest list is what rides the telemetry payload. Mutated only on
    the engine loop thread; published via an atomic list snapshot."""

    def __init__(self, prefix_len: int = 32, cap: int = 512):
        self.prefix_len = prefix_len
        self.cap = cap
        self._entries: "OrderedDict[str, Tuple[int, ...]]" = OrderedDict()
        self._digests: List[str] = []

    def register(self, tokens: Sequence[int], n_committed_tokens: int) -> None:
        """Record a committed prefix if it covers at least one full
        digest window (shorter commits can't be cluster keys — the
        router hashes prefix_len tokens)."""
        if n_committed_tokens < self.prefix_len:
            return
        d = str(prefix_digest(tokens, self.prefix_len))
        committed = tuple(int(t) for t in tokens[:n_committed_tokens])
        self._entries.pop(d, None)
        self._entries[d] = committed
        while len(self._entries) > self.cap:
            self._entries.popitem(last=False)
        self._digests = list(self._entries)

    def tokens_for(self, digest) -> Optional[Tuple[int, ...]]:
        return self._entries.get(str(digest))

    def __contains__(self, digest) -> bool:
        return str(digest) in self._entries

    def published(self) -> List[str]:
        """JSON-safe digest list for the replica's telemetry payload
        (atomic snapshot — the stat reporter runs off-loop)."""
        return self._digests
