"""Traffic-driven replica autoscaling policy.

Equivalent of the reference's autoscaling policy + config
(reference: serve/_private/autoscaling_policy.py — scale toward
``total_ongoing_requests / target_ongoing_requests`` clamped to
``[min_replicas, max_replicas]``; serve/config.py AutoscalingConfig).

Split deliberately in two:

- ``AutoscalingConfig`` — the user-facing knobs, validated ONCE at
  ``serve.deployment()`` time (unknown keys, ``min > max``,
  non-positive targets all raise a named ``ValueError`` instead of
  riding silently in the deployment record until the control loop
  trips over them).
- ``AutoscalerState`` — the per-deployment decision engine. Pure host
  logic over ``(now, load)`` observations: a smoothing window over
  recent load samples, then upscale/downscale DELAY gates so bursty
  arrivals don't flap the replica set (a decision must hold
  continuously for the whole delay window before it fires). Every
  method takes ``now`` explicitly, so unit tests drive synthetic
  queue-depth traces through it with a fake clock.

The controller feeds this from the PR-4 telemetry path (per-replica
queue depth + in-flight counts published into the ``serve`` snapshot,
the same table ``/api/serve`` serves) — the autoscaler never calls
into a replica synchronously.
"""
from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Any, Dict, Optional

# every key a user may put in autoscaling_config
_CONFIG_KEYS = (
    "min_replicas",
    "max_replicas",
    "initial_replicas",
    "target_ongoing_requests",
    "upscale_delay_s",
    "downscale_delay_s",
    "metrics_window_s",
    "upscale_smoothing_factor",
    "downscale_smoothing_factor",
    "pools",
)

# disaggregated serving pool roles (serve/_internal/kv_plane.py) and the
# per-pool autoscaling knobs each sub-config may carry. The two pools
# scale on DIFFERENT signals — prefill on queued prompt tokens (arrival
# burst pressure), decode on busy token-loop lanes (steady occupancy) —
# so each role names its own target knob and naming the wrong one is a
# config error, not a silent zero.
_POOL_NAMES = ("prefill", "decode")
_POOL_SUB_KEYS = (
    "min_replicas",
    "max_replicas",
    "target_queued_prefill_tokens",
    "target_decode_lanes",
    "upscale_delay_s",
    "downscale_delay_s",
)


@dataclasses.dataclass(frozen=True)
class AutoscalingConfig:
    """Queue-depth autoscaling knobs (reference: serve AutoscalingConfig).

    target_ongoing_requests: per-replica load the policy steers toward —
        desired replicas = ceil(total_load / target).
    upscale_delay_s / downscale_delay_s: how long a scale decision must
        hold CONTINUOUSLY before it fires (flap guard; downscale is
        slower by default so a burst's tail doesn't thrash).
    metrics_window_s: load samples are averaged over this window before
        the policy sees them (smoothing against sampling noise).
    upscale/downscale_smoothing_factor: fraction of the replica-count
        gap closed per decision (1.0 = jump straight to desired).
    min_replicas may be 0 (scale-to-zero): handles then PARK incoming
        requests and nudge the controller, which scales back to 1.
    pools: per-pool overrides for disaggregated deployments
        (pool_config on the deployment): {"prefill": {...}, "decode":
        {...}} where each sub-dict may set min/max_replicas, the
        up/downscale delays, and the pool's OWN signal target —
        target_queued_prefill_tokens for the prefill pool (scale on
        admission backlog), target_decode_lanes for the decode pool
        (scale on token-loop occupancy).
    """

    min_replicas: int = 1
    max_replicas: int = 8
    initial_replicas: Optional[int] = None
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 8.0
    metrics_window_s: float = 3.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 1.0
    pools: Optional[Dict[str, Any]] = None

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError(
                f"autoscaling_config: min_replicas must be >= 0, got "
                f"{self.min_replicas}"
            )
        if self.max_replicas < 1:
            raise ValueError(
                f"autoscaling_config: max_replicas must be >= 1, got "
                f"{self.max_replicas}"
            )
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                f"autoscaling_config: min_replicas ({self.min_replicas}) > "
                f"max_replicas ({self.max_replicas})"
            )
        if self.initial_replicas is not None and not (
            self.min_replicas <= self.initial_replicas <= self.max_replicas
        ):
            raise ValueError(
                f"autoscaling_config: initial_replicas "
                f"({self.initial_replicas}) outside "
                f"[{self.min_replicas}, {self.max_replicas}]"
            )
        if self.target_ongoing_requests <= 0:
            raise ValueError(
                f"autoscaling_config: target_ongoing_requests must be "
                f"positive, got {self.target_ongoing_requests}"
            )
        for knob in ("upscale_delay_s", "downscale_delay_s", "metrics_window_s"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"autoscaling_config: {knob} must be >= 0, got "
                    f"{getattr(self, knob)}"
                )
        for knob in ("upscale_smoothing_factor", "downscale_smoothing_factor"):
            if not (0.0 < getattr(self, knob) <= 1.0):
                raise ValueError(
                    f"autoscaling_config: {knob} must be in (0, 1], got "
                    f"{getattr(self, knob)}"
                )
        if self.pools is not None:
            self._validate_pools(self.pools)

    @staticmethod
    def _validate_pools(pools) -> None:
        if not isinstance(pools, dict):
            raise ValueError(
                f"autoscaling_config: pools must be a dict, got "
                f"{type(pools).__name__}"
            )
        unknown_pools = set(pools) - set(_POOL_NAMES)
        if unknown_pools:
            raise ValueError(
                f"autoscaling_config: unknown pool(s) "
                f"{sorted(unknown_pools)}; valid pools: "
                f"{sorted(_POOL_NAMES)}"
            )
        for role, sub in pools.items():
            if not isinstance(sub, dict):
                raise ValueError(
                    f"autoscaling_config: pools[{role!r}] must be a dict, "
                    f"got {type(sub).__name__}"
                )
            unknown = set(sub) - set(_POOL_SUB_KEYS)
            if unknown:
                raise ValueError(
                    f"autoscaling_config: pools[{role!r}]: unknown key(s) "
                    f"{sorted(unknown)}; valid keys: {sorted(_POOL_SUB_KEYS)}"
                )
            if role == "prefill" and "target_decode_lanes" in sub:
                raise ValueError(
                    "autoscaling_config: pools['prefill'] scales on "
                    "target_queued_prefill_tokens, not target_decode_lanes"
                )
            if role == "decode" and "target_queued_prefill_tokens" in sub:
                raise ValueError(
                    "autoscaling_config: pools['decode'] scales on "
                    "target_decode_lanes, not target_queued_prefill_tokens"
                )
            for knob in ("target_queued_prefill_tokens", "target_decode_lanes"):
                if knob in sub and float(sub[knob]) <= 0:
                    raise ValueError(
                        f"autoscaling_config: pools[{role!r}].{knob} must "
                        f"be positive, got {sub[knob]}"
                    )
            for knob in ("min_replicas", "max_replicas"):
                if knob in sub and int(sub[knob]) < 0:
                    raise ValueError(
                        f"autoscaling_config: pools[{role!r}].{knob} must "
                        f"be >= 0, got {sub[knob]}"
                    )
            for knob in ("upscale_delay_s", "downscale_delay_s"):
                if knob in sub and float(sub[knob]) < 0:
                    raise ValueError(
                        f"autoscaling_config: pools[{role!r}].{knob} must "
                        f"be >= 0, got {sub[knob]}"
                    )

    @property
    def start_replicas(self) -> int:
        if self.initial_replicas is not None:
            return self.initial_replicas
        return max(self.min_replicas, 1)


def validate_autoscaling_config(cfg: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Validate a user autoscaling_config dict at deployment() time.

    Returns the normalized dict (defaults filled in, JSON-safe) or None.
    Raises ValueError naming the offending key — never lets a bad config
    ride silently in the deployment record.
    """
    if cfg is None:
        return None
    if not isinstance(cfg, dict):
        raise ValueError(
            f"autoscaling_config must be a dict, got {type(cfg).__name__}"
        )
    unknown = set(cfg) - set(_CONFIG_KEYS)
    if unknown:
        raise ValueError(
            f"autoscaling_config: unknown key(s) {sorted(unknown)}; valid "
            f"keys: {sorted(_CONFIG_KEYS)}"
        )
    return dataclasses.asdict(AutoscalingConfig(**cfg))


# ------------------------------------------------------------------- pools
def validate_pool_config(cfg: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Validate a deployment pool_config ({"prefill": P, "decode": D} —
    the disaggregated-serving replica split) at deployment() time.
    Both pools are required (a prefill pool with nowhere to send its KV,
    or a decode pool nothing feeds, is always a config error) and each
    count must be >= 1."""
    if cfg is None:
        return None
    if not isinstance(cfg, dict):
        raise ValueError(
            f"pool_config must be a dict, got {type(cfg).__name__}"
        )
    unknown = set(cfg) - set(_POOL_NAMES)
    if unknown:
        raise ValueError(
            f"pool_config: unknown pool(s) {sorted(unknown)}; valid "
            f"pools: {sorted(_POOL_NAMES)}"
        )
    missing = set(_POOL_NAMES) - set(cfg)
    if missing:
        raise ValueError(
            f"pool_config: missing pool(s) {sorted(missing)} — "
            f"disaggregated serving needs both a prefill and a decode pool"
        )
    out = {}
    for role, n in cfg.items():
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            raise ValueError(
                f"pool_config: pools[{role!r}] must be an int >= 1, "
                f"got {n!r}"
            )
        out[role] = n
    return out


def pool_autoscaler_config(cfg: Dict[str, Any], role: str) -> Dict[str, Any]:
    """Project a pooled autoscaling_config onto ONE pool's standard
    AutoscalingConfig: base knobs minus `pools`, overlaid with the
    pool's sub-config, with the pool's signal target
    (target_queued_prefill_tokens / target_decode_lanes) mapped onto
    target_ongoing_requests — so the shared AutoscalerState.decide()
    engine scales toward total_signal / target without knowing which
    signal it is steering."""
    base = {k: v for k, v in cfg.items() if k != "pools"}
    # start counts come from pool_config, never from the shared knob
    base.pop("initial_replicas", None)
    sub = dict((cfg.get("pools") or {}).get(role) or {})
    target = sub.pop("target_queued_prefill_tokens",
                     sub.pop("target_decode_lanes", None))
    if target is not None:
        base["target_ongoing_requests"] = float(target)
    base.update(sub)
    return base


# ---------------------------------------------------------------- affinity
_AFFINITY_KEYS = ("prefix_len", "spill_threshold", "vnodes", "mode",
                  "cluster")


@dataclasses.dataclass(frozen=True)
class AffinityConfig:
    """Cache-affinity routing knobs (handle/proxy consistent-hash ring).

    prefix_len: how much of the prompt feeds the affinity digest —
        leading tokens for list prompts, leading characters for string
        prompts. Must cover the shared system prompt for repeat traffic
        to land on the cache-hot replica.
    spill_threshold: outstanding requests on the preferred replica at
        which routing spills to least-loaded instead (cache affinity
        must not become a hotspot amplifier).
    vnodes: virtual nodes per replica on the hash ring (built once per
        membership refresh; more = smoother key redistribution).
    mode: "auto" (session_id when the request carries one, else prompt
        prefix), "session" (session_id only), "prefix" (prompt only).
    cluster: consult the cluster-wide KV inventory
        (serve/_internal/kv_plane.InventoryView) before the hash ring —
        a prefix prefilled ANYWHERE routes its repeat traffic to the
        replica that owns the blocks. Off = ring-only routing.
    """

    prefix_len: int = 32
    spill_threshold: int = 8
    vnodes: int = 32
    mode: str = "auto"
    cluster: bool = True

    def __post_init__(self):
        if self.prefix_len < 1:
            raise ValueError(
                f"affinity_config: prefix_len must be >= 1, got {self.prefix_len}"
            )
        if self.spill_threshold < 1:
            raise ValueError(
                f"affinity_config: spill_threshold must be >= 1, got "
                f"{self.spill_threshold}"
            )
        if self.vnodes < 1:
            raise ValueError(
                f"affinity_config: vnodes must be >= 1, got {self.vnodes}"
            )
        if self.mode not in ("auto", "session", "prefix"):
            raise ValueError(
                f"affinity_config: mode must be one of 'auto', 'session', "
                f"'prefix', got {self.mode!r}"
            )


def validate_affinity_config(cfg: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Validate a user affinity_config dict at deployment() time."""
    if cfg is None:
        return None
    if not isinstance(cfg, dict):
        raise ValueError(
            f"affinity_config must be a dict, got {type(cfg).__name__}"
        )
    unknown = set(cfg) - set(_AFFINITY_KEYS)
    if unknown:
        raise ValueError(
            f"affinity_config: unknown key(s) {sorted(unknown)}; valid "
            f"keys: {sorted(_AFFINITY_KEYS)}"
        )
    return dataclasses.asdict(AffinityConfig(**cfg))


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Failure-semantics knobs (handle redispatch policy). Rides the
    same controller long-poll payload as the affinity config, so every
    handle learns the deployment's policy with its membership.

    redispatch: auto-requeue a request that was in flight on a replica
        that DIED (process kill / wedge declared dead) onto a survivor.
        Safe only for side-effect-free requests — result delivery is
        end-of-request only, so nothing can have escaped a killed
        replica, but a side-effectful method may have partially
        executed. Off by default; llm_deployment (pure generation)
        turns it on.
    max_redispatches: automatic requeue attempts per request before the
        failure surfaces as a typed retryable ReplicaDiedError.
    """

    redispatch: bool = False
    max_redispatches: int = 1

    def __post_init__(self):
        if self.max_redispatches < 0:
            raise ValueError(
                f"fault_config: max_redispatches must be >= 0, got "
                f"{self.max_redispatches}"
            )


_FAULT_KEYS = tuple(f.name for f in dataclasses.fields(FaultConfig))


def validate_fault_config(cfg: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Validate a user fault_config dict at deployment() time."""
    if cfg is None:
        return None
    if not isinstance(cfg, dict):
        raise ValueError(
            f"fault_config must be a dict, got {type(cfg).__name__}"
        )
    unknown = set(cfg) - set(_FAULT_KEYS)
    if unknown:
        raise ValueError(
            f"fault_config: unknown key(s) {sorted(unknown)}; valid "
            f"keys: {sorted(_FAULT_KEYS)}"
        )
    return dataclasses.asdict(FaultConfig(**cfg))


# ------------------------------------------------------------ decision state
class AutoscalerState:
    """Per-deployment autoscaling decision engine.

    ``decide(total_load, current, now)`` is the whole protocol: feed it
    the deployment's summed load (queue depth + in-flight across
    replicas) and the current replica count; it returns the replica
    count to scale to (== current when no change should happen yet).

    Flap guard: raw desired != current starts a directional timer; the
    decision fires only after desired stays on that side of current for
    the full up/downscale delay. Any tick where the direction flips (or
    equals current) resets the timers, so an oscillating load signal
    holds the replica set steady instead of thrashing it.
    """

    def __init__(self, cfg: AutoscalingConfig):
        if isinstance(cfg, dict):
            cfg = AutoscalingConfig(**cfg)
        self.cfg = cfg
        self._window: deque = deque()  # (now, load) samples
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        # decision bookkeeping for introspection / status endpoints
        self.last_load: float = 0.0
        self.last_desired: int = 0

    # -- observations ---------------------------------------------------
    def _observe(self, load: float, now: float) -> float:
        """Append a sample, trim the window, return the smoothed load."""
        self._window.append((now, float(load)))
        horizon = now - self.cfg.metrics_window_s
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()
        return sum(s for _, s in self._window) / len(self._window)

    # -- policy ---------------------------------------------------------
    def _raw_desired(self, avg_load: float, current: int) -> int:
        """ceil(load/target), smoothing factors applied to the delta,
        clamped to [min, max]."""
        cfg = self.cfg
        want = math.ceil(avg_load / cfg.target_ongoing_requests - 1e-9)
        if want > current:
            step = math.ceil((want - current) * cfg.upscale_smoothing_factor)
            want = current + max(1, step)
        elif want < current:
            step = math.ceil((current - want) * cfg.downscale_smoothing_factor)
            want = current - max(1, step)
        return max(cfg.min_replicas, min(cfg.max_replicas, want))

    def decide(self, total_load: float, current: int, now: float) -> int:
        """One autoscaler tick. Returns the target replica count."""
        avg = self._observe(total_load, now)
        desired = self._raw_desired(avg, current)
        self.last_load = avg
        self.last_desired = desired
        if desired > current:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= self.cfg.upscale_delay_s:
                self._above_since = None
                return desired
            return current
        if desired < current:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= self.cfg.downscale_delay_s:
                self._below_since = None
                return desired
            return current
        self._above_since = None
        self._below_since = None
        return current

    def reset(self) -> None:
        """Forget history (called after an external scale event such as
        a redeploy, so stale samples don't drive the next decision)."""
        self._window.clear()
        self._above_since = None
        self._below_since = None
