"""Sampling parameters for the serve request path.

The host half of real sampling: a validated, immutable parameter set
that rides a request from serve/llm.py through the engine into the
macro plan, where it is compiled into the per-phase f32/i32 plan
arrays (temperature/top_k/top_p per slot, stop-token id rows padded
with -1) that models/llama_decode.sample_tokens consumes device-side.

Greedy is temperature == 0.0 (the default), which keeps every
pre-sampling caller's behavior bit-identical: sample_tokens lowers to
argmax for those lanes, and a plan whose requests are all greedy is
still value-independent end to end.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

# fixed width of the device-side stop-id rows ((B, MAX_STOP_TOKENS) i32,
# -1 padded). A static bound keeps the jit cache keyed only on plan
# geometry; 4 covers eos + the usual chat-template stop ids.
MAX_STOP_TOKENS = 4


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decode controls.

    temperature: 0.0 => greedy argmax (deterministic); > 0 scales logits
        before categorical sampling.
    top_k: keep only the k highest logits (0 => disabled/full vocab).
    top_p: nucleus sampling — keep the smallest set of tokens whose
        cumulative probability reaches top_p (1.0 => disabled).
    seed: per-request PRNG seed, or None (the default) to let the
        engine draw a fresh one per request — two seedless sampled
        requests must NOT share a token stream. With an explicit seed,
        sampling is reproducible per request REGARDLESS of
        co-scheduling: the slot's key is seeded from it at admission
        and split once per decode step, so batch composition never
        changes a request's tokens.
    stop: token ids that end generation early (the stop token itself is
        not delivered). Detected device-side; the host repairs its
        speculative plan when the resolved tokens reveal the stop.
    deadline: ABSOLUTE unix time (time.time() seconds) after which the
        result is worthless to the caller. Not a sampling control — it
        rides here because this dataclass is the per-request record
        that travels handle → replica → engine, and the engine's
        admission/shed policy is its consumer: requests still queued
        past their deadline are shed with a typed error instead of
        burning decode steps, and admission refuses requests whose
        queue ETA already overruns the budget. Request dicts set it via
        the relative ``deadline_s`` field (the handle stamps the
        absolute form so redispatch can't reset the clock).
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: "int | None" = None
    stop: Tuple[int, ...] = ()
    deadline: "float | None" = None

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        stop = tuple(int(t) for t in self.stop)
        if len(stop) > MAX_STOP_TOKENS:
            raise ValueError(
                f"at most {MAX_STOP_TOKENS} stop tokens supported, got {len(stop)}"
            )
        if any(t < 0 for t in stop):
            raise ValueError(f"stop token ids must be >= 0, got {stop}")
        object.__setattr__(self, "stop", stop)
        if self.deadline is not None and self.deadline <= 0:
            raise ValueError(
                f"deadline must be an absolute unix time > 0, got "
                f"{self.deadline} (request dicts carry the relative form "
                f"as 'deadline_s')"
            )

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def stop_row(self) -> Tuple[int, ...]:
        """Fixed-width stop-id row for the device plan (-1 = unused)."""
        return self.stop + (-1,) * (MAX_STOP_TOKENS - len(self.stop))

    @classmethod
    def from_request(cls, obj) -> "SamplingParams":
        """Coerce a request-path value: None (greedy default), an
        existing SamplingParams, or a dict of fields."""
        if obj is None:
            return cls()
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls(**obj)
        raise TypeError(f"cannot build SamplingParams from {type(obj).__name__}")
