"""Radix prefix cache: committed prompt prefixes -> refcounted block runs.

The RadixAttention idea (SGLang, Zheng et al.) at KV-block granularity:
a token trie whose edges are FULL blocks of `block_size` prompt tokens
and whose nodes pin the KV block holding that chunk's keys/values.
An admission that shares a system prompt with any earlier request walks
the trie, takes read-only references on the matched block run, and
prefills only its suffix — the shared prefill is skipped entirely.

Sharing rules (what keeps this correct without device-side locks):

- FULL blocks only. A partial last block is private to its sequence
  (decode appends into it), so it is never inserted; matched prefixes
  are therefore always block-aligned, which is exactly the alignment
  the device-side suffix prefill requires of its start positions.
- A lookup is capped at len(prompt) - 1 tokens: even a 100% cached
  prompt must prefill its final token, because the first output token
  is sampled from the last prompt position's logits.
- Insertion happens at admission PLAN time, not completion: the blocks
  are filled by the same (or an earlier) phase of the very macro-step
  the plan compiles to, and device phases execute in plan order, so a
  later admission in the same dispatch can already share them. Within
  one admission batch the layer body writes every row's suffix K/V
  before any row gathers context, so even same-phase sharers read the
  owner's writes.
- Eviction is LRU over LEAF nodes whose block nobody but the cache
  references (refcount 1): interior nodes are pinned by their children,
  in-use blocks by their requests. evict() walks leaves until it freed
  the requested count or ran out of evictable leaves.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.serve._internal.kv_blocks import BlockAllocator


class _Node:
    __slots__ = ("chunk", "block", "children", "parent", "tick")

    def __init__(self, chunk: Tuple[int, ...], block: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.tick = 0


class RadixPrefixCache:
    """Block-granular token trie over a BlockAllocator.

    Single-threaded like the allocator (engine-loop only). Counters are
    plain ints read by metrics() under the GIL.
    """

    def __init__(self, allocator: BlockAllocator):
        self._alloc = allocator
        self._bs = allocator.block_size
        self._root = _Node((), -1, None)
        self._tick = 0
        self._nodes = 0
        # token-level counters: reuse rate = hit_tokens / lookup_tokens
        self.hits = 0          # lookups that matched >= 1 block
        self.misses = 0
        self.evictions = 0     # blocks evicted
        self.hit_tokens = 0
        self.lookup_tokens = 0

    # ----------------------------------------------------------- lookup
    def lookup(self, prompt: Sequence[int], record: bool = True
               ) -> Tuple[List[int], int]:
        """Longest cached block-aligned proper prefix of `prompt`.
        Returns (blocks, matched_tokens); every returned block carries a
        NEW reference owned by the caller (released when the request's
        table is freed). matched_tokens < len(prompt) always.

        record=False skips the hit/miss counters (LRU ticks still
        touch): the engine retries a pool-exhausted admission every plan
        tick, and those repeats must not inflate the hit rate — it calls
        record_lookup() once when the admission actually lands."""
        n_full = (len(prompt) - 1) // self._bs  # proper prefix: >= 1 token left
        node, blocks = self._root, []
        self._tick += 1
        for i in range(n_full):
            chunk = tuple(prompt[i * self._bs:(i + 1) * self._bs])
            child = node.children.get(chunk)
            if child is None:
                break
            child.tick = self._tick
            blocks.append(child.block)
            node = child
        if record:
            self.record_lookup(len(prompt), len(blocks))
        if blocks:
            self._alloc.incref(blocks)
        return blocks, len(blocks) * self._bs

    def match_blocks(self, tokens: Sequence[int]) -> List[int]:
        """Non-mutating full-block walk for the KV-plane EXPORT path:
        every committed block covering `tokens` (all len//bs of them —
        unlike lookup(), which caps at the proper prefix because an
        admission must re-prefill its last token). No references are
        taken and no counters/ticks move: the caller gathers the blocks
        in the same engine-loop closure, before any other allocator
        mutation can recycle them."""
        node, out = self._root, []
        n_full = len(tokens) // self._bs
        for i in range(n_full):
            chunk = tuple(tokens[i * self._bs:(i + 1) * self._bs])
            child = node.children.get(chunk)
            if child is None:
                break
            out.append(child.block)
            node = child
        return out

    def record_lookup(self, n_prompt_tokens: int, n_matched_blocks: int) -> None:
        """Count one lookup toward the hit/miss/reuse-rate stats."""
        self.lookup_tokens += n_prompt_tokens
        if n_matched_blocks:
            self.hits += 1
            self.hit_tokens += n_matched_blocks * self._bs
        else:
            self.misses += 1

    # ----------------------------------------------------------- insert
    def insert(self, prompt: Sequence[int], table: Sequence[int]) -> int:
        """Commit `prompt`'s full blocks (backed by table[i]) into the
        trie. Existing nodes are left alone (first writer wins — the
        duplicate blocks stay private to their request and free when it
        finishes); new nodes take one cache-owned reference. Returns the
        number of newly committed blocks."""
        n_full = len(prompt) // self._bs
        node, added = self._root, 0
        self._tick += 1
        for i in range(n_full):
            chunk = tuple(prompt[i * self._bs:(i + 1) * self._bs])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, table[i], node)
                node.children[chunk] = child
                self._alloc.incref([table[i]])
                self._nodes += 1
                added += 1
            child.tick = self._tick
            node = child
        return added

    # ------------------------------------------------------------ evict
    def evict(self, n_blocks: int) -> int:
        """Free up to `n_blocks` pool blocks by dropping LRU leaves whose
        block only the cache still references. Returns blocks actually
        freed (0 when nothing is evictable — callers must re-check the
        pool, not assume success).

        One DFS collects ALL evictable leaves, sorted LRU-first, and the
        batch is consumed in order (a per-block full-trie walk would be
        O(n_blocks x nodes) on the engine-loop admission path); the
        outer loop only re-walks when evicting a leaf exposed its parent
        as newly evictable."""
        freed = 0
        while freed < n_blocks:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            for node in leaves:
                if freed >= n_blocks:
                    break
                del node.parent.children[node.chunk]
                self._nodes -= 1
                self.evictions += 1
                freed += len(self._alloc.decref([node.block]))
        return freed

    def _evictable_leaves(self) -> List[_Node]:
        """Leaves whose block only the cache references, LRU-first."""
        out = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is self._root or node.children:
                continue
            if self._alloc.refcount(node.block) != 1:
                continue  # a live request still reads it
            out.append(node)
        out.sort(key=lambda n: n.tick)
        return out

    def clear(self) -> int:
        """Drop every node (cache references only). Returns blocks freed."""
        freed = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            freed += len(self._alloc.decref([node.block]))
            self._nodes -= 1
        self._root.children.clear()
        return freed

    # ----------------------------------------------------------- status
    @property
    def nodes(self) -> int:
        return self._nodes

    def stats(self) -> Dict[str, float]:
        total = max(1, self.lookup_tokens)
        return {
            "prefix_cache_nodes": self._nodes,
            "prefix_cache_hits": self.hits,
            "prefix_cache_misses": self.misses,
            "prefix_cache_evictions": self.evictions,
            "prefix_cache_hit_rate": round(self.hit_tokens / total, 4),
            # raw token counters so multi-replica aggregation can compute
            # a token-weighted hit rate instead of averaging ratios
            "prefix_cache_hit_tokens": self.hit_tokens,
            "prefix_cache_lookup_tokens": self.lookup_tokens,
        }
