"""serve._internal — paged-KV serving internals.

Host-side machinery behind the continuous-batching engine's paged mode:
the block allocator (kv_blocks), the radix prefix cache (prefix_cache)
and the sampling-parameter plumbing (sampling). Device-side paged
attention lives in models/llama_decode.py; these modules never import
jax — they are pure host bookkeeping that compiles block tables and
sampling plans into the i32/f32 program arguments the device programs
consume.
"""
from ray_tpu.serve._internal.kv_blocks import (  # noqa: F401
    NULL_BLOCK,
    BlockAllocator,
    BlockPoolExhausted,
)
from ray_tpu.serve._internal.prefix_cache import RadixPrefixCache  # noqa: F401
from ray_tpu.serve._internal.sampling import SamplingParams  # noqa: F401
