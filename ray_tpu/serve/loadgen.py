"""Open-loop load harness for serve deployments.

The missing piece between "fast single engine" and "serves heavy
traffic": an OPEN-LOOP Poisson-arrival generator (arrivals fire on the
exponential clock regardless of completions — closed-loop generators
self-throttle exactly when the system saturates, hiding the latency
cliff the measurement exists to find) that drives a
``DeploymentHandle`` through configurable phases (steady state, a
traffic burst that trips the autoscaler's scale-up, a drain window
that trips scale-down) and reports:

- client-side request latency p50/p99 and goodput tokens/s per phase,
- zero-drop accounting (every arrival is tracked to completion or a
  counted error — a scale event that strands a request is visible),
- the replica-count timeline sampled during the run (scale-up /
  scale-down events land in the report),
- engine-side TTFT/TPOT percentiles and per-replica prefix-cache hit
  rates, read back through the same telemetry table ``/api/serve``
  serves (plus an exact per-replica metrics scrape for tests).

Workloads mix prompt/output lengths from uniform ranges and carry an
optional SHARED SYSTEM PROMPT mixture: ``shared_fraction`` of requests
start with ``shared_prefix``, which is what cache-affinity routing and
the radix prefix cache are for — the aggregate hit rate with affinity
on vs off is the headline A/B.

Requests ride asyncio (one event loop, thousands of in-flight awaits —
no thread per client), submitting through the handle's normal
``remote()`` path so routing, affinity and the direct transport all
engage exactly as production traffic would.
"""
from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Workload",
    "Phase",
    "run_load",
    "serve_snapshot",
    "aggregate_prefix_cache",
    "replica_metrics",
]


@dataclasses.dataclass
class Workload:
    """What each arrival sends.

    rate_hz: base Poisson arrival rate (phases scale it).
    prompt_len / max_new_tokens: uniform [lo, hi] per request.
    shared_prefix + shared_fraction: that fraction of prompts starts
        with the shared token prefix (the "system prompt" mixture).
    session_count: > 0 tags requests with one of N session ids
        (session-affinity routing exercises the session path).
    session_prefixes + session_prefix_len: K DISTINCT per-session
        prefixes (each session's prompts share session-specific leading
        tokens, and carry that session's id). This is the workload
        where cache-affinity routing matters most: with K prefixes
        spread over R replicas, affinity partitions them K/R per
        replica while unaffinitized routing makes every replica cache
        (and under pool pressure, evict) all K.
    deadline_s: > 0 stamps every request with this relative deadline
        budget (the handle converts it to the absolute form) — the
        engine's deadline-aware admission/shed path engages exactly as
        it would for production traffic carrying deadlines.
    request_fn: escape hatch — build the request yourself (rng ->
        request object); everything above is ignored. Use for non-LLM
        deployments.
    count_tokens: result -> generated-token count for goodput (defaults
        to len(result) for list results, else 0).
    """

    rate_hz: float = 20.0
    prompt_len: Tuple[int, int] = (4, 12)
    max_new_tokens: Tuple[int, int] = (4, 8)
    vocab: int = 50
    shared_prefix: Sequence[int] = ()
    shared_fraction: float = 0.0
    session_count: int = 0
    session_prefixes: int = 0
    session_prefix_len: int = 16
    deadline_s: Optional[float] = None
    seed: int = 0
    request_fn: Optional[Callable[[random.Random], Any]] = None
    count_tokens: Optional[Callable[[Any], int]] = None


@dataclasses.dataclass
class Phase:
    """One load phase: `rate_multiplier` scales the workload's base
    rate (0.0 = send nothing, just observe — the drain window)."""

    name: str
    duration_s: float
    rate_multiplier: float = 1.0


def _make_request(w: Workload, rng: random.Random):
    if w.request_fn is not None:
        return w.request_fn(rng)
    plen = rng.randint(*w.prompt_len)
    body = [rng.randrange(1, w.vocab) for _ in range(max(1, plen))]
    req: Dict[str, Any] = {
        "max_new_tokens": rng.randint(*w.max_new_tokens),
    }
    if w.deadline_s is not None:
        req["deadline_s"] = w.deadline_s
    if w.session_prefixes > 0:
        # per-session distinct prefixes: session s always opens with its
        # own session_prefix_len tokens (deterministic, disjoint from
        # the random-body vocab so sessions never alias)
        s = rng.randrange(w.session_prefixes)
        req["prompt"] = [w.vocab + s] * w.session_prefix_len + body
        req["session_id"] = f"session-{s}"
        return req
    if w.shared_prefix and rng.random() < w.shared_fraction:
        req["prompt"] = list(w.shared_prefix) + body
    else:
        req["prompt"] = body
    if w.session_count > 0:
        req["session_id"] = f"session-{rng.randrange(w.session_count)}"
    return req


def _count_tokens(w: Workload, result: Any) -> int:
    if w.count_tokens is not None:
        try:
            return int(w.count_tokens(result))
        except Exception:
            return 0
    return len(result) if isinstance(result, (list, tuple)) else 0


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


# ------------------------------------------------------------- telemetry
def serve_snapshot() -> Dict[str, Any]:
    """The merged `serve` telemetry table — the same data `/api/serve`
    serves: `replica:<name>` load stats, `engine:<name>` serving
    metrics, `autoscaler:<app>::<dep>` decisions."""
    from ray_tpu.observability import fetch_snapshots

    merged: Dict[str, Any] = {}
    for snap in fetch_snapshots("serve").values():
        if not isinstance(snap, dict):
            continue
        for key, val in snap.items():
            if key in ("time", "steps"):
                continue
            merged[key] = val
    return merged


def aggregate_prefix_cache(snapshot: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Token-weighted aggregate prefix-cache hit rate across every
    engine entry in a serve snapshot (or per-replica metrics dict)."""
    snapshot = serve_snapshot() if snapshot is None else snapshot
    hit = lookup = hits = misses = 0
    per_replica: Dict[str, float] = {}
    for key, m in snapshot.items():
        if not isinstance(m, dict) or "prefix_cache_hit_rate" not in m:
            continue
        hit += int(m.get("prefix_cache_hit_tokens", 0))
        lookup += int(m.get("prefix_cache_lookup_tokens", 0))
        hits += int(m.get("prefix_cache_hits", 0))
        misses += int(m.get("prefix_cache_misses", 0))
        per_replica[key] = m["prefix_cache_hit_rate"]
    return {
        "hit_tokens": hit,
        "lookup_tokens": lookup,
        "hits": hits,
        "misses": misses,
        # token-weighted (how much prefill FLOP the cache absorbed) and
        # request-weighted (how many admissions found their prefix hot —
        # the affinity A/B discriminator: off-routing misses once PER
        # REPLICA a prefix visits, on-routing once total)
        "hit_rate": round(hit / max(1, lookup), 4),
        "request_hit_rate": round(hits / max(1, hits + misses), 4),
        "per_replica": per_replica,
    }


def replica_metrics(app_name: str, deployment_name: str) -> Dict[str, Dict[str, Any]]:
    """Exact per-replica `metrics()` scrape (driver-side harness tool —
    one RPC per replica; the controller's autoscaler never does this).
    Returns {replica_name: metrics dict} for replicas whose deployment
    exposes a `metrics` method."""
    import ray_tpu
    from ray_tpu.serve.api import _get_controller

    controller = _get_controller()
    info = ray_tpu.get(
        controller.get_replicas_versioned.remote(app_name, deployment_name)
    )
    data = info["data"]
    names = data["replicas"] if isinstance(data, dict) else (data or [])
    out: Dict[str, Dict[str, Any]] = {}
    for name in names:
        try:
            h = ray_tpu.get_actor(name)
            out[name] = ray_tpu.get(
                h.handle_request.remote("metrics", (), {}), timeout=30
            )
        except Exception:
            continue
    return out


# ------------------------------------------------------------ the harness
async def _run_async(handle, workload: Workload, phases: List[Phase],
                     request_timeout_s: float, track: Optional[Tuple[str, str]],
                     drain_timeout_s: float, retries: int = 0,
                     chaos=None, chaos_target: Optional[Tuple[str, str]] = None,
                     slo: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    rng = random.Random(workload.seed)
    records: List[Dict[str, Any]] = []
    in_flight: set = set()
    t_start = time.monotonic()
    replica_timeline: List[Tuple[float, int]] = []
    stop_sampler = asyncio.Event()

    async def _sample_replicas():
        from ray_tpu.serve import api as serve_api

        loop = asyncio.get_running_loop()
        while not stop_sampler.is_set():
            try:
                st = await loop.run_in_executor(None, serve_api.status)
                n = st.get(track[0], {}).get(track[1], {}).get("num_replicas")
                if n is not None:
                    replica_timeline.append((time.monotonic() - t_start, n))
            except Exception:
                pass
            try:
                await asyncio.wait_for(stop_sampler.wait(), timeout=0.5)
            except asyncio.TimeoutError:
                pass

    async def _one(req, phase_name: str):
        from ray_tpu.serve.errors import classify_error

        rec = {"phase": phase_name, "t_submit": time.monotonic(), "ok": False,
               "tokens": 0, "error": None, "category": None, "retried": 0}
        records.append(rec)
        attempt = 0
        while True:
            try:
                # handle.remote() is cheap in steady state (pick + ring
                # write) but can BLOCK during the scale events this
                # harness exists to measure (zero-replica parking, an
                # empty-set controller refresh) — submit on a worker
                # thread so one parked request never stalls the arrival
                # clock or other requests' completion timestamps
                resp = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: handle.remote(req)
                )
                result = await resp.async_result(request_timeout_s)
                rec["tokens"] = _count_tokens(workload, result)
                rec["ok"] = True
                rec["error"] = None
                rec["category"] = None
                # the result itself is NOT retained: a multi-minute run
                # at open-loop rates would otherwise hold every
                # generated token list until the report builds
                break
            except Exception as e:  # a failed attempt — classify it
                category, retryable, hint = classify_error(e)
                rec["error"] = f"{type(e).__name__}: {e}"
                rec["category"] = category
                # the harness retry: ONE more attempt for typed
                # retryable failures (the chaos-gate contract — a
                # request that fails retryable and lands on retry was
                # never lost). Deadline/other failures are final.
                if retryable and attempt < retries:
                    attempt += 1
                    rec["retried"] = attempt
                    if hint:
                        await asyncio.sleep(min(float(hint), 2.0))
                    continue
                break
        rec["t_done"] = time.monotonic()

    sampler = asyncio.ensure_future(_sample_replicas()) if track else None
    injector = None
    if chaos is not None:
        # the chaos phase: fault events fire on the schedule's clock,
        # relative to the first arrival — kills/hangs land mid-burst
        from ray_tpu.chaos import ServeChaosInjector

        app, dep = (chaos_target or track
                    or (handle.app_name, handle.deployment_name))
        injector = ServeChaosInjector(chaos, app, dep).start()
    for phase in phases:
        rate = workload.rate_hz * phase.rate_multiplier
        phase_end = time.monotonic() + phase.duration_s
        if rate <= 0:
            # observation window (drain): no arrivals
            await asyncio.sleep(phase.duration_s)
            continue
        while True:
            now = time.monotonic()
            if now >= phase_end:
                break
            gap = rng.expovariate(rate)
            if now + gap >= phase_end:
                await asyncio.sleep(phase_end - now)
                break
            await asyncio.sleep(gap)
            task = asyncio.ensure_future(
                _one(_make_request(workload, rng), phase.name)
            )
            in_flight.add(task)
            task.add_done_callback(in_flight.discard)
    # final drain: every arrival runs to completion or a counted error
    if in_flight:
        await asyncio.wait(list(in_flight), timeout=drain_timeout_s)
    for task in list(in_flight):
        task.cancel()
    if sampler is not None:
        stop_sampler.set()
        await sampler
    report = _build_report(records, replica_timeline,
                           time.monotonic() - t_start, slo=slo)
    if injector is not None:
        injector.stop()
        injector.join(timeout=5.0)
        report["chaos"] = {
            "scheduled": len(chaos.events),
            "fired": list(injector.fired),
        }
    return report


def _phase_stats(recs: List[Dict[str, Any]], wall_s: float,
                 slo: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    lat = sorted(
        (r["t_done"] - r["t_submit"]) * 1e3 for r in recs if r.get("ok")
    )
    tokens = sum(r["tokens"] for r in recs if r.get("ok"))
    # typed drop taxonomy (serve/errors.classify_error categories):
    # shed/deadline drops are the system REFUSING work it could not
    # finish in time — intentional, typed, fast. "Lost" is everything
    # else that didn't complete: a replica-death drop that survived the
    # harness retry budget, or an untyped failure/timeout. The chaos
    # gate is lost == 0.
    drops: Dict[str, int] = {}
    retried = recovered = 0
    for r in recs:
        if r.get("retried"):
            retried += 1
            if r.get("ok"):
                recovered += 1
        if not r.get("ok"):
            drops[r.get("category") or "other"] = (
                drops.get(r.get("category") or "other", 0) + 1)
    lost = sum(n for cat, n in drops.items() if cat not in ("shed", "deadline"))
    rej = sorted(
        (r["t_done"] - r["t_submit"]) * 1e3 for r in recs
        if not r.get("ok") and r.get("category") in ("shed", "deadline")
    )
    out = {
        "sent": len(recs),
        "completed": sum(1 for r in recs if r.get("ok")),
        "dropped": sum(1 for r in recs if not r.get("ok")),
        "drops": drops,
        "retried": retried,
        "recovered": recovered,
        "lost": lost,
        "latency_ms_p50": round(_percentile(lat, 0.50), 2),
        "latency_ms_p99": round(_percentile(lat, 0.99), 2),
        "tokens_out": tokens,
        "goodput_tok_s": round(tokens / max(1e-9, wall_s), 2),
    }
    if rej:
        # how fast overload turns into a typed rejection — the overload
        # gate wants this ≪ the request deadline
        out["rejection_ms_p99"] = round(_percentile(rej, 0.99), 2)
    target_av = (slo or {}).get("availability")
    if target_av and out["sent"]:
        # per-phase availability attainment + burn from the harness's
        # OWN request ledger (every drop — shed, deadline, lost — spends
        # error budget; burn 1.0 = spending exactly at the exhaustion
        # rate). TTFT/TPOT attainment is engine-measured: see the
        # report-level "slo" snapshots.
        observed = out["completed"] / out["sent"]
        out["slo"] = {"availability": {
            "target": target_av,
            "observed": round(observed, 6),
            "attained": bool(observed >= target_av),
            "burn_rate": round((out["dropped"] / out["sent"])
                               / max(1e-9, 1.0 - target_av), 3),
        }}
    return out


def _build_report(records, replica_timeline, wall_s,
                  slo: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    by_phase: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if "t_done" not in r:  # cancelled straggler past drain timeout
            r["t_done"] = r["t_submit"]
            r["ok"] = False
            r.setdefault("error", "TimeoutError: still in flight at drain timeout")
            r.setdefault("category", "other")
        by_phase.setdefault(r["phase"], []).append(r)
    phase_wall: Dict[str, float] = {}
    for name, recs in by_phase.items():
        t0 = min(r["t_submit"] for r in recs)
        t1 = max(r["t_done"] for r in recs)
        phase_wall[name] = max(1e-9, t1 - t0)
    report = {
        "total": _phase_stats(records, wall_s, slo=slo),
        "phases": {
            name: _phase_stats(recs, phase_wall[name], slo=slo)
            for name, recs in by_phase.items()
        },
        "errors": sorted({r["error"] for r in records if r.get("error")})[:8],
        "wall_s": round(wall_s, 2),
    }
    if replica_timeline:
        report["replicas_timeline"] = [
            (round(t, 2), n) for t, n in replica_timeline
        ]
        report["replicas_peak"] = max(n for _, n in replica_timeline)
        report["replicas_final"] = replica_timeline[-1][1]
    return report


def run_load(handle, workload: Workload, phases: Optional[List[Phase]] = None,
             *, request_timeout_s: float = 60.0,
             track: Optional[Tuple[str, str]] = None,
             drain_timeout_s: float = 120.0,
             collect_serve_metrics: bool = True,
             retries: int = 0,
             chaos=None,
             chaos_target: Optional[Tuple[str, str]] = None,
             slo: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Drive `handle` with the workload through the phases (default: one
    steady phase of 5s) and return the report dict. `track=(app, dep)`
    samples that deployment's replica count through the run (the
    scale-up/scale-down record). With `collect_serve_metrics`, the
    report carries the post-run `/api/serve`-path telemetry snapshot
    (engine TTFT/TPOT percentiles, aggregate prefix-cache hit rate).

    Failure knobs: `retries` grants each arrival that many extra
    attempts on TYPED-RETRYABLE failures (shed / replica death) — the
    chaos-gate contract is retries=1 with zero `lost`. `chaos` takes a
    ray_tpu.chaos.ChaosSchedule fired against `chaos_target` (defaults
    to `track`, then the handle's own deployment) while the load runs;
    the report's `chaos` section records what actually fired, and every
    drop is classified shed / replica-death / deadline / other.

    `slo` passes per-phase availability targets explicitly; when None,
    the tracked/handle deployment's deployed `slo_config` is discovered
    from serve.status() — each phase then reports its own attainment
    and burn rate alongside the cluster-wide `slo:` snapshots."""
    phases = phases or [Phase("steady", 5.0)]
    # epoch fence: stamp the serve telemetry table NOW so every snapshot
    # this run reads comes from a reporter that published during/after
    # it — a deleted deployment's engines (GCS keeps a dead reporter's
    # last write ≤120s) can no longer contaminate an A/B rerun
    try:
        from ray_tpu import observability as _obs

        _obs.reset_epoch("serve")
    except Exception:
        pass
    if slo is None:
        # discover the deployment's deployed objectives (status() carries
        # the evaluator's config once the control loop has ticked)
        try:
            from ray_tpu.serve import api as _api

            app, dep = track or (handle.app_name, handle.deployment_name)
            st = _api.status().get(app, {}).get(dep, {})
            slo = (st.get("slo") or {}).get("config")
        except Exception:
            slo = None
    report = asyncio.run(
        _run_async(handle, workload, phases, request_timeout_s, track,
                   drain_timeout_s, retries=retries, chaos=chaos,
                   chaos_target=chaos_target, slo=slo)
    )
    if collect_serve_metrics:
        time.sleep(0.5)  # let the last engine/replica publishes land
        snap = serve_snapshot()
        # prefix-cache headline straight from the (now epoch-fenced)
        # snapshot — the round-8 live-replica scrape survives only as a
        # fallback for the window where fenced reporters haven't
        # republished yet. Custom request_fn workloads (non-LLM
        # deployments) never scrape — probing `metrics` on a deployment
        # without one spews remote AttributeErrors into the worker logs.
        pc = aggregate_prefix_cache(snap)
        if not pc["per_replica"] and workload.request_fn is None:
            try:
                pc = aggregate_prefix_cache(
                    replica_metrics(handle.app_name, handle.deployment_name)
                )
            except Exception:
                pass
        report["prefix_cache"] = pc
        report["engines"] = {
            k: {
                m: v[m]
                for m in ("ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                          "tpot_ms_p99", "queue_depth",
                          "prefix_cache_hit_rate", "tokens_out")
                if m in v
            }
            for k, v in snap.items()
            if isinstance(v, dict) and k.startswith("engine:")
        }
        report["autoscaler"] = {
            k: v for k, v in snap.items() if k.startswith("autoscaler:")
        }
        # the controller-evaluated SLO plane: attainment + multi-window
        # burn rates per deployment (engine-measured TTFT/TPOT p99s —
        # the per-phase blocks above cover availability only)
        slo_snaps = {k: v for k, v in snap.items() if k.startswith("slo:")}
        if slo_snaps:
            report["slo"] = slo_snaps
    return report
