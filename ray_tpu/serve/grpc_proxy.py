"""gRPC proxy for Serve.

Equivalent of the reference's gRPC proxy (reference:
serve/_private/proxy.py:542 gRPCProxy — a grpc.aio server sharing the
HTTP proxy's routing/handle layer). Without protoc-generated stubs in
the image, the service is a generic bytes-in/bytes-out handler with a
msgpack envelope — the same routing table (controller long-poll) and the
same DeploymentHandle data path as the HTTP proxy.

Wire contract (all msgpack):
    request : {"app": str, "deployment": str?, "method": str?,
               "args": list?, "kwargs": dict?}
      or    : {"route": "/prefix", ...} to resolve via the route table
    response: {"result": ...} | {"error": str}

Client example::

    ch = grpc.insecure_channel("localhost:9000")
    call = ch.unary_unary("/ray_tpu.serve.Serve/Call")
    reply = msgpack.unpackb(call(msgpack.packb({"app": "default", "args": [x]})))
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Dict, Optional

import ray_tpu

SERVICE_METHOD = "/ray_tpu.serve.Serve/Call"


@ray_tpu.remote(num_cpus=0)
class GrpcProxyActor:
    """grpc server on a dedicated thread; requests route through cached
    DeploymentHandles exactly like the HTTP proxy's."""

    def __init__(self, port: int = 9000):
        import grpc
        import msgpack

        self.port = port
        self.routes: Dict[str, tuple] = {}
        self._routes_version = 0
        self._handles: Dict[tuple, Any] = {}
        self._msgpack = msgpack

        proxy = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != SERVICE_METHOD:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    proxy._call,
                    request_deserializer=None,  # raw bytes
                    response_serializer=None,
                )

        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=8),
            handlers=(_Handler(),),
        )
        bound = self._server.add_insecure_port(f"0.0.0.0:{port}")
        if bound == 0:
            raise RuntimeError(f"grpc proxy failed to bind port {port}")
        self.port = bound
        self._server.start()
        self._poller = threading.Thread(target=self._routes_poll_loop, daemon=True, name="grpc-longpoll")
        self._poller.start()

    # -- routing (same long-poll freshness as the HTTP proxy) -----------
    def _routes_poll_loop(self):
        import time as _t

        from ray_tpu.serve.api import _get_controller

        while True:
            try:
                controller = _get_controller()
                changed = ray_tpu.get(
                    controller.listen_for_change.remote(
                        {"routes": self._routes_version}, timeout_s=20.0
                    ),
                    timeout=40.0,
                )
                if "routes" in changed:
                    self.routes = dict(changed["routes"]["data"])
                    self._routes_version = changed["routes"]["version"]
            except Exception:
                _t.sleep(1.0)

    def _handle_for(self, app_name: str, dep_name: Optional[str], method: str):
        from ray_tpu.serve.api import _get_controller
        from ray_tpu.serve.handle import DeploymentHandle

        if dep_name is None:
            # latest deployment of the app (reference: app-level ingress)
            controller = _get_controller()
            st = ray_tpu.get(controller.status.remote())
            deps = list(st.get(app_name, {}))
            if not deps:
                raise ValueError(f"no app {app_name!r}")
            dep_name = deps[-1]
        key = (app_name, dep_name, method)
        h = self._handles.get(key)
        if h is None:
            h = DeploymentHandle(dep_name, app_name)
            h._method = method
            h._refresh()
            self._handles[key] = h
        return h

    def _call(self, request_bytes: bytes, context) -> bytes:
        m = self._msgpack
        try:
            req = m.unpackb(request_bytes, raw=False)
            app_name = req.get("app", "default")
            dep_name = req.get("deployment")
            if dep_name is None and req.get("route"):
                route = self.routes.get(req["route"])
                if route is not None:
                    app_name, dep_name = route[0], route[1]
            h = self._handle_for(app_name, dep_name, req.get("method", "__call__"))
            resp = h.remote(*req.get("args", ()), **req.get("kwargs", {}))
            return m.packb({"result": resp.result(timeout=60)}, use_bin_type=True)
        except Exception as e:
            return m.packb({"error": f"{type(e).__name__}: {e}"}, use_bin_type=True)

    def ready(self):
        return self.port


def start_grpc_proxy(port: int = 9000):
    """Start (or return) the gRPC proxy actor; returns (actor, port)."""
    name = "SERVE_GRPC_PROXY"
    try:
        actor = ray_tpu.get_actor(name)
    except ValueError:
        actor = GrpcProxyActor.options(name=name, lifetime="detached").remote(port)
    return actor, ray_tpu.get(actor.ready.remote())
