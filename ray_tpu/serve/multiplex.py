"""Model multiplexing: many models share a replica pool.

Equivalent of the reference's serve.multiplexed / get_multiplexed_model_id
(reference: python/ray/serve/multiplex.py _ModelMultiplexWrapper — a
per-replica LRU of loaded models keyed by the request's model id; and
api.py get_multiplexed_model_id). Routing affinity comes from
rendezvous hashing on the model id (handle.py) so the same model keeps
landing on the same replicas and the LRU actually hits — the reference
gets the same effect by reporting loaded-model sets through long-poll;
hashing needs no state push and behaves identically under a stable
replica set, which on a TPU serving pod it is.
"""
from __future__ import annotations

import collections
import contextvars
import functools
import inspect
import threading
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)
_mux_states: dict = {}  # (module, qualname) -> {"lock", "cache"}, per process


def _get_mux_state(state_key) -> dict:
    st = _mux_states.get(state_key)
    if st is None:
        st = _mux_states[state_key] = {
            "lock": threading.Lock(),
            "cache": collections.OrderedDict(),
        }
    return st


def get_multiplexed_model_id() -> str:
    """The model id of the request currently being handled
    (reference: serve/api.py get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    return _current_model_id.set(model_id)


def _run_coroutine(coro):
    """Run an async model loader to completion whether or not the caller
    is already inside an event loop (an async deployment handler runs
    under asyncio.run in the replica — a nested asyncio.run would raise)."""
    import asyncio

    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    result: dict = {}

    def runner():
        try:
            result["value"] = asyncio.run(coro)
        except BaseException as e:
            result["error"] = e

    t = threading.Thread(target=runner, name="multiplex-loader")
    t.start()
    t.join()
    if "error" in result:
        raise result["error"]
    return result["value"]


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate a model-loader method `def get_model(self, model_id)`;
    calls are LRU-cached per replica, evicting the least-recently-used
    model beyond `max_num_models_per_replica`."""

    def deco(fn: Callable):
        # LRU state lives OUTSIDE the function/class (created lazily per
        # process, keyed per decoration — factory-made wrappers share a
        # qualname but must not share a cache) and is looked up via a
        # NAMED module function, which cloudpickle ships by reference:
        # a closure over the state (or the registry dict) would drag its
        # locks into the deployment class's pickle
        import uuid

        state_key = (fn.__module__, fn.__qualname__, uuid.uuid4().hex)

        @functools.wraps(fn)
        def wrapper(self_or_id, *rest):
            if rest:
                owner, model_id = self_or_id, rest[0]
            else:
                owner, model_id = None, self_or_id
            st = _get_mux_state(state_key)
            lock, cache = st["lock"], st["cache"]
            with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
            model = fn(owner, model_id) if owner is not None else fn(model_id)
            if inspect.iscoroutine(model):
                model = _run_coroutine(model)
            with lock:
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    evicted_id, evicted = cache.popitem(last=False)
                    del_fn = getattr(evicted, "__del__", None)
                    if del_fn is not None:
                        try:
                            del_fn()
                        except Exception:
                            pass
            return model

        wrapper._multiplexed_state_key = state_key  # introspection / tests
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
