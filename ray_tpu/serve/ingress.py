"""HTTP ingress for Serve deployments: method-level route decorators and
path routing inside a deployment class.

Equivalent of the reference's FastAPI integration (reference:
python/ray/serve/api.py @serve.ingress — there a FastAPI app is mounted
inside the replica and the proxy forwards raw ASGI scope; FastAPI is not
in this image, so the router here is a small native route table with
`{param}` path captures, and the proxy forwards (method, path, body,
query) to the replica's dispatcher).

Usage::

    @serve.deployment
    @serve.ingress
    class Api:
        @serve.route("GET", "/hello/{name}")
        def hello(self, name):
            return {"msg": f"hi {name}"}

        @serve.route("POST", "/items")
        def create(self, body):        # `body` receives the JSON payload
            return {"ok": True, "item": body}

`serve.run(Api.bind(), route_prefix="/api")` serves GET /api/hello/x and
POST /api/items through the shared HTTP proxy.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

_ROUTE_ATTR = "__serve_route__"


def route(http_method: str, pattern: str):
    """Mark a method as an HTTP route inside an @serve.ingress class.
    `pattern` is /-separated; `{name}` segments capture into kwargs."""

    def deco(fn):
        routes = getattr(fn, _ROUTE_ATTR, [])
        routes.append((http_method.upper(), pattern))
        setattr(fn, _ROUTE_ATTR, routes)
        return fn

    return deco


def _compile(pattern: str) -> List[str]:
    return [seg for seg in pattern.strip("/").split("/") if seg != ""]


def _match(segs: List[str], path: str) -> Optional[Dict[str, str]]:
    parts = [p for p in path.strip("/").split("/") if p != ""]
    if len(parts) != len(segs):
        return None
    captures: Dict[str, str] = {}
    for seg, part in zip(segs, parts):
        if seg.startswith("{") and seg.endswith("}"):
            captures[seg[1:-1]] = part
        elif seg != part:
            return None
    return captures


def ingress(cls):
    """Class decorator: collect @serve.route-marked methods into a route
    table and install the dispatcher the HTTP/gRPC proxies call."""
    table: List[Tuple[str, List[str], str]] = []  # (http_method, segs, attr)
    for attr in dir(cls):
        fn = getattr(cls, attr, None)
        for http_method, pattern in getattr(fn, _ROUTE_ATTR, ()):
            table.append((http_method, _compile(pattern), attr))

    def __serve_http_request__(self, http_method: str, path: str,
                               body: Any = None, query: Optional[Dict[str, str]] = None):
        import inspect

        for m, segs, attr in table:
            if m != http_method.upper():
                continue
            captures = _match(segs, path)
            if captures is None:
                continue
            fn = getattr(self, attr)
            kwargs: Dict[str, Any] = dict(captures)
            sig = inspect.signature(fn)
            if "body" in sig.parameters:
                kwargs["body"] = body
            if "query" in sig.parameters:
                kwargs["query"] = query or {}
            return fn(**kwargs)
        raise _NoRouteError(f"no route for {http_method} {path}")

    cls.__serve_http_request__ = __serve_http_request__
    cls.__serve_is_ingress__ = True
    return cls


class _NoRouteError(Exception):
    """Raised by the dispatcher for unmatched paths; the proxy maps it to
    a 404 instead of a 500."""
