"""Serve public API.

Equivalent of the reference's serve.api (reference: serve/api.py:439
serve.run; @serve.deployment decorator; serve/batching.py @serve.batch).
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeControllerActor
from ray_tpu.serve.handle import DeploymentHandle

_controller_lock = threading.Lock()


def _get_controller(create: bool = False):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise RuntimeError("serve is not running (no controller)")
    with _controller_lock:
        try:
            return ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:
            ServeControllerActor.options(name=CONTROLLER_NAME, lifetime="detached", num_cpus=0).remote()
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    return ray_tpu.get_actor(CONTROLLER_NAME)
                except ValueError:
                    time.sleep(0.1)
            raise RuntimeError("serve controller failed to start")


class Application:
    """A bound deployment, possibly with other bound deployments among its
    init args — the deployment-graph form (reference: serve deployment
    graphs / model composition, serve/api.py build + handle passing:
    children deploy first and the parent receives DeploymentHandles)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(
        self,
        cls_or_fn,
        name: Optional[str] = None,
        num_replicas: int = 1,
        route_prefix: Optional[str] = None,
        ray_actor_options: Optional[dict] = None,
        max_ongoing_requests: int = 16,
        autoscaling_config: Optional[dict] = None,
        affinity_config: Optional[dict] = None,
        fault_config: Optional[dict] = None,
        pool_config: Optional[dict] = None,
        slo_config: Optional[dict] = None,
    ):
        from ray_tpu.serve._internal.autoscaler import (
            validate_affinity_config,
            validate_autoscaling_config,
            validate_fault_config,
            validate_pool_config,
        )
        from ray_tpu.serve._internal.slo import validate_slo_config

        self._callable = cls_or_fn
        self.name = name or getattr(cls_or_fn, "__name__", "deployment")
        self.num_replicas = num_replicas
        self.route_prefix = route_prefix
        self.ray_actor_options = ray_actor_options or {}
        self.max_ongoing_requests = max_ongoing_requests
        # {"min_replicas", "max_replicas", "target_ongoing_requests",
        #  "initial_replicas", delay/smoothing knobs} — traffic-driven
        # autoscaling (reference: serve autoscaling_config on
        # @serve.deployment). Validated HERE: unknown keys, min > max or
        # non-positive targets raise at deployment() time, not after the
        # record already shipped to the controller.
        self.autoscaling_config = validate_autoscaling_config(autoscaling_config)
        # {"prefix_len", "spill_threshold", "vnodes", "mode"} —
        # cache-affinity routing: same-prefix/same-session traffic
        # consistently hashes to the replica whose radix cache is hot
        self.affinity_config = validate_affinity_config(affinity_config)
        # {"redispatch", "max_redispatches"} — failure semantics: may
        # the handle requeue a dead replica's in-flight requests onto
        # survivors? (safe only for side-effect-free requests; see
        # serve/errors.py for the full taxonomy)
        self.fault_config = validate_fault_config(fault_config)
        # {"prefill": P, "decode": D} — disaggregated serving: the
        # deployment runs two replica pools with distinct roles joined
        # by the KV plane (serve/_internal/kv_plane.py); replica counts
        # here REPLACE num_replicas
        self.pool_config = validate_pool_config(pool_config)
        # {"ttft_p99_ms", "tpot_p99_ms", "availability"} — serving
        # objectives: the controller evaluates attainment + burn rates
        # each tick and publishes `slo:<app>::<dep>` snapshots
        # (serve/_internal/slo.py). Validated HERE, same contract as the
        # other configs: bad targets raise at deployment() time.
        self.slo_config = validate_slo_config(slo_config)
        if self.pool_config is not None:
            self.num_replicas = sum(self.pool_config.values())
        if (self.autoscaling_config or {}).get("pools") and self.pool_config is None:
            raise ValueError(
                "autoscaling_config['pools'] requires pool_config on the "
                "deployment (per-pool targets without pools to apply "
                "them to)"
            )

    def options(self, **kw) -> "Deployment":
        merged = dict(
            name=self.name,
            num_replicas=self.num_replicas,
            route_prefix=self.route_prefix,
            ray_actor_options=self.ray_actor_options,
            max_ongoing_requests=self.max_ongoing_requests,
            autoscaling_config=self.autoscaling_config,
            affinity_config=self.affinity_config,
            fault_config=self.fault_config,
            pool_config=self.pool_config,
            slo_config=self.slo_config,
        )
        merged.update(kw)
        return Deployment(self._callable, **merged)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_cls=None, **kwargs):
    """@serve.deployment decorator."""

    def wrap(cls):
        return Deployment(cls, **kwargs)

    if _cls is not None:
        return wrap(_cls)
    return wrap


def _deploy_tree(controller, app_name: str, app: Application, *, is_root: bool,
                 root_prefix: Optional[str], seen: Dict[int, str]) -> str:
    """Post-order deploy of a deployment graph: children first, each
    Application arg replaced by a handle marker the Replica resolves at
    init (reference: deployment graphs — serve handles passed into
    constructors)."""
    import cloudpickle

    if id(app) in seen:  # diamond: same bound child used twice
        return seen[id(app)]
    dep = app.deployment

    def _resolve(v):
        if isinstance(v, Application):
            child = _deploy_tree(
                controller, app_name, v, is_root=False, root_prefix=None, seen=seen
            )
            return {"__serve_handle__": [app_name, child]}
        return v

    init_args = tuple(_resolve(a) for a in app.init_args)
    init_kwargs = {k: _resolve(v) for k, v in app.init_kwargs.items()}
    prefix = None
    if is_root:
        prefix = dep.route_prefix if dep.route_prefix is not None else root_prefix
    ray_tpu.get(
        controller.deploy.remote(
            app_name,
            dep.name,
            cloudpickle.dumps(dep._callable),
            init_args,
            init_kwargs,
            dep.num_replicas,
            prefix,
            dep.ray_actor_options,
            dep.autoscaling_config,
            bool(getattr(dep._callable, "__serve_is_ingress__", False)),
            dep.affinity_config,
            dep.fault_config,
            dep.pool_config,
            dep.slo_config,
        )
    )
    seen[id(app)] = dep.name
    return dep.name


def run(app: Application, *, name: str = "default", route_prefix: Optional[str] = "/") -> DeploymentHandle:
    """Deploy an application — a single bound deployment or a whole
    deployment graph (reference: serve/api.py:439)."""
    controller = _get_controller(create=True)
    root = _deploy_tree(
        controller, name, app, is_root=True, root_prefix=route_prefix, seen={}
    )
    # fire-and-forget: the controller's reconcile/autoscale loop (idempotent)
    controller.run_control_loop.remote()
    handle = DeploymentHandle(root, name)
    handle._refresh()
    return handle


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    handle = DeploymentHandle(deployment_name, app_name)
    handle._refresh()
    return handle


def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    controller = _get_controller()
    st = ray_tpu.get(controller.status.remote())
    deps = list(st.get(app_name, {}))
    if not deps:
        raise ValueError(f"no app {app_name}")
    return get_deployment_handle(deps[-1], app_name)


def delete(app_name: str = "default"):
    controller = _get_controller()
    ray_tpu.get(controller.delete_app.remote(app_name))


def status() -> Dict[str, Any]:
    controller = _get_controller()
    return ray_tpu.get(controller.status.remote())


def request_timeline(rid: str) -> List[Dict[str, Any]]:
    """The cluster-wide lifeline of one request id: driver-process
    events (handle-side submit/route/redispatch) merged with the
    controller's per-replica fan-out (engine-side admit/dispatch/
    kv_export/resume/finish — the prefill→decode migration hop stitches
    because the rid survives it), time-sorted."""
    from ray_tpu.observability import lifeline

    merged = [dict(e) for e in lifeline.events(rid)]
    try:
        controller = _get_controller()
        merged.extend(ray_tpu.get(controller.request_timeline.remote(rid)))
    except Exception:
        pass
    merged.sort(key=lambda e: e.get("t", 0.0))
    return merged


def shutdown():
    try:
        controller = _get_controller()
    except RuntimeError:
        return
    st = ray_tpu.get(controller.status.remote())
    for app_name in list(st):
        ray_tpu.get(controller.delete_app.remote(app_name))
    ray_tpu.kill(controller)


# --------------------------------------------------------------- batching
_batch_states: Dict[Any, Dict[str, Any]] = {}  # (module, qualname) -> state


def _get_batch_state(state_key) -> Dict[str, Any]:
    """Lazy per-process state. A module-level NAMED function on purpose:
    cloudpickle ships it by reference, so the decorated wrapper's pickle
    never drags in `_batch_states` (whose Conditions are unpicklable the
    moment any batch function has run in this process)."""
    st = _batch_states.get(state_key)
    if st is None:
        st = _batch_states[state_key] = {
            "cond": threading.Condition(),
            "pending": [],
        }
    return st


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01,
          wait_timeout_s: float = 300.0):
    """@serve.batch — coalesce concurrent calls into one batched call
    (reference: python/ray/serve/batching.py). The leader waits on a
    condition variable — woken early the instant the batch fills — rather
    than burning a thread in a sleep/poll loop."""

    def deco(fn):
        # per-process lazy state; the key carries a per-DECORATION token
        # (factory-made wrappers share a qualname but must not share a
        # queue) and travels inside the wrapper's pickled closure, so a
        # shipped replica resolves the same state its driver-side twin
        # would (see _get_batch_state for why the lookup is a named
        # module function)
        import uuid

        state_key = (fn.__module__, fn.__qualname__, uuid.uuid4().hex)

        @functools.wraps(fn)
        def wrapper(self_or_item, *rest):
            st = _get_batch_state(state_key)
            cond: threading.Condition = st["cond"]
            pending: List = st["pending"]  # (args_item, event, out)
            # method form: (self, item); function form: (item,)
            if rest:
                owner, item = self_or_item, rest[0]
            else:
                owner, item = None, self_or_item
            ev = threading.Event()
            slot: Dict[str, Any] = {}
            with cond:
                pending.append((item, ev, slot))
                leader = len(pending) == 1
                if len(pending) >= max_batch_size:
                    cond.notify_all()  # wake the leader early: batch full
            if leader:
                while True:
                    with cond:
                        deadline = time.monotonic() + batch_wait_timeout_s
                        while len(pending) < max_batch_size:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0 or not cond.wait(timeout=remaining):
                                break
                        batch_items = pending[:max_batch_size]
                        del pending[: len(batch_items)]
                    if not batch_items:
                        break
                    items = [b[0] for b in batch_items]
                    try:
                        results = fn(owner, items) if owner is not None else fn(items)
                        for (_, e, s), r in zip(batch_items, results):
                            s["result"] = r
                            e.set()
                    except Exception as exc:
                        for _, e, s in batch_items:
                            s["error"] = exc
                            e.set()
                    with cond:
                        if not pending:
                            break
            if not ev.wait(timeout=wait_timeout_s):
                raise TimeoutError("batched call timed out")
            if "error" in slot:
                raise slot["error"]
            return slot["result"]

        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
