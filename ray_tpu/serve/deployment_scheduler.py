"""Serve replica placement policy.

Equivalent of the reference's deployment scheduler
(reference: python/ray/serve/_private/deployment_scheduler.py —
SpreadDeploymentSchedulingPolicy spreads replicas across nodes;
compact/affinity strategies pack them). TPU-first twist: deployments
that request TPU chips PACK onto the fewest nodes (replica traffic then
rides intra-slice ICI and a node's chips serve one model copy), while
CPU deployments SPREAD for fault isolation — losing one node loses
1/N replicas, not all of them.

The scheduler tracks its own placements so spreading is balanced from
the first replica (the GCS actor table only reflects started actors),
and it records the node-grouped drain order that versioned upgrades
follow (drain one node fully before touching the next — reference:
serve's node-by-node rolling updates honoring draining nodes).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


class DeploymentScheduler:
    def __init__(self):
        # replica name -> node_id chosen for it
        self._placed: Dict[str, str] = {}

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _alive_nodes() -> List[Dict[str, Any]]:
        try:
            from ray_tpu.util.state import list_nodes

            return [n for n in list_nodes() if n.get("state") == "ALIVE"]
        except Exception:
            return []

    @staticmethod
    def _deployment_key(replica_name: str) -> str:
        # replica names are SERVE_REPLICA::<app>::<deployment>::<n>
        return "::".join(replica_name.split("::")[:3])

    def _count_on(self, node_id: str, deployment_key: str) -> int:
        """Count only THIS deployment's replicas: spreading must balance
        per deployment, or a new deployment's replicas all land on
        whichever node other apps left empty."""
        return sum(
            1 for name, nid in self._placed.items()
            if nid == node_id and self._deployment_key(name) == deployment_key
        )

    # ------------------------------------------------------------ policy
    def place(self, replica_name: str, actor_options: Dict[str, Any]) -> Dict[str, Any]:
        """Returns the actor options extended with a scheduling strategy.

        - explicit user strategy: passed through untouched
        - TPU replicas: PACK — fill the node with the most free chips
        - default: SPREAD — least-loaded alive node by tracked count
        """
        if "scheduling_strategy" in actor_options:
            return actor_options
        nodes = self._alive_nodes()
        if not nodes:
            return actor_options
        tpu_need = float((actor_options.get("resources") or {}).get("TPU", 0))
        out = dict(actor_options)
        key = self._deployment_key(replica_name)
        if tpu_need > 0:
            fits = [
                n for n in nodes
                if n.get("resources_available", {}).get("TPU", 0) >= tpu_need
            ]
            if fits:
                # pack: most replicas already here first, then most free chips
                best = max(fits, key=lambda n: (
                    self._count_on(n["node_id"], key),
                    n["resources_available"].get("TPU", 0),
                ))
                self._placed[replica_name] = best["node_id"]
                out["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                    best["node_id"], soft=True
                )
            return out
        best = min(nodes, key=lambda n: (self._count_on(n["node_id"], key), n["node_id"]))
        self._placed[replica_name] = best["node_id"]
        out["scheduling_strategy"] = NodeAffinitySchedulingStrategy(best["node_id"], soft=True)
        return out

    def forget(self, replica_name: str) -> None:
        self._placed.pop(replica_name, None)

    @staticmethod
    def downscale_order(names: List[str], loads: Optional[Dict[str, float]] = None) -> List[str]:
        """Victim order for a scale-down: least-loaded first (fewest
        stranded requests, shortest drain), newest first on ties — the
        oldest replicas have the hottest caches and the affinity ring
        keeps steering repeat traffic at them, so they die last."""
        ranked = sorted(
            enumerate(names),
            key=lambda item: ((loads or {}).get(item[1], 0.0), -item[0]),
        )
        return [name for _, name in ranked]

    def drain_groups(self, replica_names: List[str]) -> List[List[str]]:
        """Group replicas by node for node-by-node draining; replicas with
        no tracked node drain last, together."""
        by_node: Dict[Optional[str], List[str]] = {}
        for name in replica_names:
            by_node.setdefault(self._placed.get(name), []).append(name)
        unknown = by_node.pop(None, None)
        groups = [by_node[k] for k in sorted(by_node)]
        if unknown:
            groups.append(unknown)
        return groups
