"""Typed serve failure taxonomy.

The serving plane's failure story hangs off ONE vocabulary: every way
an accepted request can fail maps to a class here, every class says
whether a retry can succeed, and every layer (engine admission, handle
redispatch, HTTP proxy, loadgen report) speaks it instead of inventing
its own string matching. Errors raised replica-side cross the process
boundary as themselves — the RPC and direct-transport reply envelopes
cloudpickle the exception object (`core_worker._env_err` /
`_rebuild_error`) — so `isinstance` works wherever the failure lands.

Retryable means: the request provably produced no observable output,
so resubmitting it cannot duplicate anything. Three cases qualify:

- ``RequestShedError`` — admission control refused the request before
  any work started (queue bound / ETA bound). Retry after
  ``retry_after_s`` (the proxy turns this into HTTP 503 +
  ``Retry-After``).
- ``ReplicaDiedError`` with ``started=False`` — the replica died (or
  its transport broke) with the request in flight but, because result
  delivery is end-of-request only, nothing ever escaped the dead
  process. The handle auto-redispatches these onto survivors when the
  deployment opted in (``fault_config={"redispatch": True}``).
- ``ReplicaDiedError`` with ``started=True`` — the engine failed the
  request AFTER emitting tokens (engine-internal death mid-stream).
  Never auto-redispatched — a silent re-generation could diverge from
  output a streaming consumer already saw — but safe for the CALLER to
  retry explicitly, which is why it stays retryable.

``DeadlineExceededError`` is typed but NOT retryable: the client's
deadline already passed, so a retry of the same request is wasted work
by definition (retry with a fresh deadline is a new request).
"""
from __future__ import annotations

from typing import Optional, Tuple

from ray_tpu.exceptions import (
    ActorDiedError,
    ActorError,
    ActorUnavailableError,
    RayTpuError,
    TaskError,
)

__all__ = [
    "RequestRetryableError",
    "RequestShedError",
    "ReplicaDiedError",
    "DeadlineExceededError",
    "classify_error",
]


class RequestRetryableError(RayTpuError):
    """Base: the request produced no observable output — a retry (by
    the handle's redispatch or by the caller) cannot duplicate work."""

    #: hint for the caller / the proxy's Retry-After header (seconds)
    retry_after_s: float = 1.0

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class RequestShedError(RequestRetryableError):
    """Admission control refused the request (queue depth / ETA bound):
    the deployment is overloaded and queueing longer would only convert
    the overload into a timeout pileup. Maps to HTTP 503."""


class ReplicaDiedError(RequestRetryableError, RuntimeError):
    """The replica serving this request died (SIGKILL, wedge declared
    dead by the health check, engine-loop death). ``started`` records
    whether the engine had already emitted tokens for the request when
    it failed — the redispatch-safety bit (see module docstring).

    Also a RuntimeError: engine-death diagnostics historically surfaced
    that way and callers catching RuntimeError keep working."""

    def __init__(self, message: str, retry_after_s: float = 0.5,
                 started: bool = False):
        super().__init__(message, retry_after_s)
        self.started = started


class DeadlineExceededError(RayTpuError):
    """The request's deadline passed before (or while) it was served.
    Typed so the proxy can answer 504 without a stack trace; not
    retryable — the budget is spent."""


# error classes whose appearance means "the replica process/transport is
# gone" — nothing escaped, redispatch-safe unless the error itself says
# otherwise (ReplicaDiedError.started)
_DEATH_TYPES = (ActorUnavailableError, ActorDiedError, ActorError)
_DEATH_NAMES = ("ActorUnavailableError", "ActorDiedError", "ActorError",
                "ReplicaDiedError")


def classify_error(exc: BaseException) -> Tuple[str, bool, Optional[float]]:
    """Map any failure surfaced by the serve request path to
    ``(category, retryable, retry_after_s)``.

    category is one of ``"shed"`` / ``"replica-death"`` /
    ``"deadline"`` / ``"other"`` — the drop taxonomy the loadgen report
    and the proxy's HTTP mapping share. ``retry_after_s`` is None when
    the error carries no hint.

    Typed classes classify by isinstance; a ``TaskError`` (an exception
    that failed to unpickle on the way back) falls back to its recorded
    cause type so the taxonomy degrades gracefully instead of lumping
    everything into "other".
    """
    if isinstance(exc, RequestShedError):
        return "shed", True, exc.retry_after_s
    if isinstance(exc, ReplicaDiedError):
        return "replica-death", True, exc.retry_after_s
    if isinstance(exc, DeadlineExceededError):
        return "deadline", False, None
    if isinstance(exc, _DEATH_TYPES):
        return "replica-death", True, 0.5
    if isinstance(exc, TaskError):
        cause = exc.cause_type or ""
        if cause == "RequestShedError":
            return "shed", True, 1.0
        if cause == "DeadlineExceededError":
            return "deadline", False, None
        if cause in _DEATH_NAMES:
            return "replica-death", True, 0.5
    return "other", False, None
