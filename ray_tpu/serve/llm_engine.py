"""Continuous-batching LLM engine with macro-step scheduling.

The reference's Serve LLM stack delegates the decode loop to vLLM
inside replicas (continuous batching + paged KV); there is no TPU
engine to wrap, so this is the green-field TPU-native equivalent
(SURVEY §7 step 10). Design:

- A fixed pool of KV-cache SLOTS (models/llama_decode.py per-slot
  machinery): each slot is an independent sequence at its own position.
- KEY INVARIANT: greedy decode to a requested length means scheduling
  never depends on token VALUES — admission, eviction and chunk sizing
  are all decidable from host-side counters alone.
- MACRO-STEP SCHEDULING exploits that invariant to collapse dispatch
  count: the host plans K phases of admissions/evictions ahead, then
  executes the WHOLE plan as one jitted dispatch
  (llama_decode.macro_step_slots — a lax.scan over the plan whose
  phases run a fused admission prefill + a decode chunk device-side).
  Prompts ride along as program arguments, so admission costs zero
  extra dispatches.
- ADAPTIVE CHUNKS: each phase decodes exactly to the next scheduling
  event — min(chunk, min remaining over live slots) — so a freed slot
  is re-admitted at the very next phase instead of idling to a fixed
  chunk boundary; phases beyond their planned steps are skipped via
  lax.cond, so a shrunk phase costs only its real steps.
- ASYNC PIPELINE: tokens are fetched ONE MACRO-STEP BEHIND the
  dispatch frontier — while macro-step N executes, the host plans and
  dispatches N+1 from counters, then resolves N's tokens overlapped
  with N+1's compute.

Dispatch-cost math (why macro-stepping wins): with per-chunk
dispatching, serving G tokens through B slots at chunk C costs
~G/(B*C) chunk dispatches + one prefill dispatch per admission bucket;
every dispatch pays the host-link fixed cost D, so relay-attached
chips (D >> step time) lose to static batching's one-scan-per-group
even though continuous batching wastes far fewer lanes at mixed
lengths (round-5 bench: 0.31x). Macro-stepping divides the chunk
dispatches by K and folds the prefill dispatches into the same
program, so total dispatch overhead drops ~K*(1 + prefills/chunks)x —
an order of magnitude at K=8 — while the lane-efficiency win of
iteration-level scheduling is kept (and sharpened by adaptive chunks).
`metrics()` reports dispatches/token, lane occupancy and TTFT/TPOT
percentiles so bench.py can track the regime per round.

Static batching (llama_decode.generate) remains the one-shot path; the
legacy per-chunk loop survives behind macro_phases=0 for A/B testing.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "tokens", "done", "error",
                 "_first_dev", "_remaining", "_t_submit", "_t_first", "_t_done")

    def __init__(self, prompt, max_new_tokens):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.done = threading.Event()
        self.error: Optional[str] = None
        self._first_dev = None   # device scalar: prefill's first token (legacy path)
        self._remaining = 0      # host-side plan counter (decode steps owed)
        self._t_submit = time.perf_counter()
        self._t_first: Optional[float] = None
        self._t_done: Optional[float] = None


class ContinuousBatchingEngine:
    def __init__(self, params, cfg, n_slots: int = 8, max_len: int = 0,
                 chunk: int = 8, macro_phases: int = 8):
        import functools

        import jax

        from ray_tpu.models import llama_decode as D

        self._jax = jax
        self._D = D
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq_len
        self.chunk = chunk
        self.macro_phases = macro_phases  # 0 => legacy per-chunk dispatching
        self.cache = D.init_slot_cache(cfg, n_slots, self.max_len)
        self._prefill_slots = jax.jit(functools.partial(D.prefill_into_slots, cfg=cfg))
        self._chunk_fn = jax.jit(
            functools.partial(D.decode_chunk_slots, chunk=chunk, cfg=cfg),
            donate_argnums=(1,),
        )
        self._macro_fn = jax.jit(
            functools.partial(D.macro_step_slots, chunk=chunk, cfg=cfg),
            donate_argnums=(1,),
        )
        self._slots: List[Optional[_Request]] = [None] * n_slots
        import jax.numpy as jnp

        self._next_dev = jnp.zeros(n_slots, jnp.int32)  # device-side feed tokens
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._waiting: deque = deque()       # planner-side FIFO (loop thread only)
        self._pending: deque = deque()       # fetch frontier: tagged entries
        self._dead: Optional[str] = None
        # serving metrics (monotonic counters + latency samples)
        self._m = {"dispatches": 0, "tokens_out": 0, "slot_steps": 0,
                   "useful_slot_steps": 0}
        # bounded latency windows: a long-lived replica must not grow a
        # sample per request forever (percentiles stay recent-weighted)
        self._ttft: deque = deque(maxlen=2048)
        self._tpot: deque = deque(maxlen=2048)
        self._wake = threading.Event()
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public
    def submit(self, prompt: List[int], max_new_tokens: int) -> _Request:
        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        if len(prompt) == 0:
            # length 0 is the macro plan's padding-row sentinel (and the
            # legacy prefill's last-position logits would be garbage)
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt)}+{max_new_tokens}) exceeds "
                f"engine max_len {self.max_len}"
            )
        req = _Request([int(t) for t in prompt], max_new_tokens)
        self._queue.put(req)
        if self._dead is not None:
            # lost the race with the loop dying: the dead loop will never
            # drain the queue, so fail the request here instead of letting
            # the caller eat a generic timeout
            req.error = f"engine is dead: {self._dead}"
            req.done.set()
            raise RuntimeError(req.error)
        self._wake.set()
        return req

    def generate(self, prompt: List[int], max_new_tokens: int,
                 timeout: float = 120.0) -> List[int]:
        req = self.submit(prompt, max_new_tokens)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise RuntimeError(f"generation failed: {req.error}")
        return req.tokens

    def shutdown(self):
        self._running = False
        self._wake.set()
        self._thread.join(timeout=10)

    def metrics(self) -> Dict[str, Any]:
        """Serving metrics since construction (or reset_metrics()):
        dispatch counts, dispatches/token, lane occupancy %, TTFT/TPOT
        percentiles. Tokens count at DELIVERY, so read after requests
        complete for exact ratios."""
        m = dict(self._m)
        toks = max(1, m["tokens_out"])
        m["dispatches_per_token"] = round(m["dispatches"] / toks, 4)
        m["lane_occupancy_pct"] = round(
            100.0 * m["useful_slot_steps"] / max(1, m["slot_steps"]), 1
        )

        def pct(xs, q):
            if not xs:
                return None
            s = sorted(xs)
            return round(s[min(len(s) - 1, int(q * len(s)))] * 1e3, 2)

        # snapshot: the engine loop thread appends to these deques while
        # we sort (deque iteration raises on concurrent mutation; retry
        # the copy — appends are GIL-atomic so a clean pass converges)
        ttft, tpot = [], []
        for _ in range(8):
            try:
                ttft, tpot = list(self._ttft), list(self._tpot)
                break
            except RuntimeError:
                continue
        m["ttft_ms_p50"] = pct(ttft, 0.50)
        m["ttft_ms_p95"] = pct(ttft, 0.95)
        m["tpot_ms_p50"] = pct(tpot, 0.50)
        m["tpot_ms_p95"] = pct(tpot, 0.95)
        return m

    def reset_metrics(self) -> None:
        self._m = {k: 0 for k in self._m}
        self._ttft, self._tpot = deque(maxlen=2048), deque(maxlen=2048)

    # ------------------------------------------------------------ engine
    def _bucket(self, n: int) -> int:
        """Power-of-two padded prompt width, clamped to max_len: with a
        non-power-of-two max_len (e.g. 768) the raw bucket can exceed
        the cache depth and crash prefill at trace time; submit()
        already guarantees the prompt itself fits."""
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    # ---- macro-step scheduling ----------------------------------------
    def _plan(self) -> Optional[List[Dict[str, Any]]]:
        """Plan up to macro_phases phases of admissions + adaptive decode
        chunks purely from host counters (the scheduling-never-depends-
        on-token-values invariant). Mutates engine bookkeeping to the
        post-macro-step state: slot assignments, per-request remaining
        counters, evictions."""
        phases = []
        while len(phases) < self.macro_phases:
            admissions = []
            free = [i for i, r in enumerate(self._slots) if r is None]
            while free and self._waiting:
                slot = free.pop(0)
                req = self._waiting.popleft()
                req._remaining = req.max_new_tokens - 1
                self._slots[slot] = req
                admissions.append((slot, req))
            live = [(s, r) for s, r in enumerate(self._slots)
                    if r is not None and r._remaining > 0]
            if not live and not admissions:
                break
            # adaptive chunk: decode exactly to the next scheduling event
            # (a slot finishing) so the freed lane re-admits immediately
            steps = min([self.chunk] + [r._remaining for _, r in live]) if live else 0
            # invariant: steps <= every live remaining, so each live slot
            # takes exactly `steps` real tokens this phase
            takes = []
            for s, r in live:
                r._remaining -= steps
                takes.append((s, r, steps))
            for s, r in enumerate(self._slots):
                if r is not None and r._remaining == 0:
                    self._slots[s] = None  # evict: freed for the next phase
            phases.append({"steps": steps, "admissions": admissions,
                           "takes": takes})
        return phases or None

    def _dispatch_macro(self, phases: List[Dict[str, Any]]) -> None:
        """Ship the plan as ONE jitted dispatch and append the result to
        the fetch frontier (resolved one macro-step behind)."""
        import jax.numpy as jnp

        K = self.macro_phases
        max_admit = max((len(p["admissions"]) for p in phases), default=0)
        A = 1
        while A < max(1, max_admit):
            A *= 2
        P = self._bucket(max(
            (len(r.prompt) for p in phases for _, r in p["admissions"]), default=1
        ))
        steps = np.zeros(K, np.int32)
        has_admit = np.zeros(K, bool)
        prompts = np.zeros((K, A, P), np.int32)
        lengths = np.zeros((K, A), np.int32)
        slots = np.zeros((K, A), np.int32)
        rems = np.zeros((K, A), np.int32)
        for k, ph in enumerate(phases):
            steps[k] = ph["steps"]
            for a, (slot, req) in enumerate(ph["admissions"]):
                has_admit[k] = True
                prompts[k, a, : len(req.prompt)] = req.prompt
                lengths[k, a] = len(req.prompt)
                slots[k, a] = slot
                rems[k, a] = req.max_new_tokens - 1
        try:
            toks_dev, firsts_dev, self._next_dev, self.cache = self._macro_fn(
                self.params, self.cache, self._next_dev,
                jnp.asarray(steps), jnp.asarray(has_admit), jnp.asarray(prompts),
                jnp.asarray(lengths), jnp.asarray(slots), jnp.asarray(rems),
            )
        except Exception:
            # park the plan so _die can fail requests whose ONLY remaining
            # reference is this plan (admitted AND fully planned-out slots
            # are already evicted from the host bookkeeping)
            self._pending.append(("macro", None, None, phases))
            raise
        self._m["dispatches"] += 1
        for ph in phases:
            self._m["slot_steps"] += ph["steps"] * self.n_slots
            self._m["useful_slot_steps"] += sum(t for _, _, t in ph["takes"])
        self._pending.append(("macro", toks_dev, firsts_dev, phases))

    def _loop_macro(self) -> None:
        while self._running:
            self._drain_queue()
            if not self._waiting and not any(r is not None for r in self._slots):
                while self._pending:
                    self._resolve(self._pending.popleft())
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            phases = self._plan()
            if phases:
                self._dispatch_macro(phases)
            # fetch one macro-step BEHIND: overlaps the one just dispatched
            while len(self._pending) > 1:
                self._resolve(self._pending.popleft())

    # ---- legacy per-chunk path (macro_phases=0): kept for A/B tests ----
    def _admit(self) -> None:
        """Move queued requests into free slots. Admissions are BATCHED:
        requests bucket by power-of-two padded prompt length and each
        bucket prefills in ONE dispatch (prefill_into_slots) — over a
        relay-attached TPU a dispatch costs ~100x its compute, so
        per-sequence prefills would dominate the whole engine."""
        import jax.numpy as jnp

        free = [i for i, r in enumerate(self._slots) if r is None]
        batch: List[tuple] = []
        while free and self._waiting:
            slot, req = free.pop(0), self._waiting.popleft()
            # claim the slot BEFORE the prefill dispatch so a failed
            # dispatch still leaves the request reachable by _die
            self._slots[slot] = req
            batch.append((slot, req))
        if not batch:
            return
        buckets: Dict[int, List[tuple]] = {}
        for slot, req in batch:
            buckets.setdefault(self._bucket(len(req.prompt)), []).append((slot, req))
        for tb, members in buckets.items():
            prompts = np.zeros((len(members), tb), np.int32)
            lengths = np.zeros(len(members), np.int32)
            slots = np.zeros(len(members), np.int32)
            for n, (slot, req) in enumerate(members):
                prompts[n, : len(req.prompt)] = req.prompt
                lengths[n] = len(req.prompt)
                slots[n] = slot
            firsts, self.cache = self._prefill_slots(
                self.params, jnp.asarray(prompts), jnp.asarray(lengths),
                jnp.asarray(slots), self.cache,
            )
            self._m["dispatches"] += 1
            rem_updates = np.zeros(len(members), np.int32)
            for n, (_slot, req) in enumerate(members):
                req._first_dev = firsts[n]
                req._remaining = req.max_new_tokens - 1
                rem_updates[n] = req._remaining
            self.cache["remaining"] = self.cache["remaining"].at[
                jnp.asarray(slots)
            ].set(jnp.asarray(rem_updates))
            live = [n for n, (_s, r) in enumerate(members) if r._remaining > 0]
            if live:
                idx = jnp.asarray(slots[live])
                self._next_dev = self._next_dev.at[idx].set(firsts[jnp.asarray(live)])

    def _loop_chunked(self) -> None:
        while self._running:
            self._drain_queue()
            self._admit()
            active = [(s, r) for s, r in enumerate(self._slots) if r is not None]
            if not active:
                while self._pending:
                    self._resolve(self._pending.popleft())
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            # prefill-only requests resolve without a decode chunk
            takes = []
            for slot, req in active:
                if req._remaining == 0:
                    takes.append((slot, req, 0))
                    self._slots[slot] = None
            if len(takes) == len(active):
                self._pending.append(("chunk", None, takes))
                continue
            # dispatch the next chunk fed from device-side tokens (no sync)
            toks_dev, self.cache = self._chunk_fn(self.params, self.cache, self._next_dev)
            self._next_dev = toks_dev[:, -1]
            self._m["dispatches"] += 1
            self._m["slot_steps"] += self.chunk * self.n_slots
            # deterministic bookkeeping: plan takes + evictions from
            # host counters — token values never gate scheduling
            for slot, req in active:
                if req._remaining == 0:
                    continue
                take = min(req._remaining, self.chunk)
                req._remaining -= take
                self._m["useful_slot_steps"] += take
                takes.append((slot, req, take))
                if req._remaining == 0:
                    self._slots[slot] = None  # evict: freed for next admit
            self._pending.append(("chunk", toks_dev, takes))
            # fetch one chunk BEHIND: overlaps the chunk just dispatched
            while len(self._pending) > 1:
                self._resolve(self._pending.popleft())

    # ---- shared plumbing ----------------------------------------------
    def _drain_queue(self) -> None:
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                return

    def _deliver(self, req: _Request, toks) -> None:
        if req._t_first is None and (req.tokens or toks):
            req._t_first = time.perf_counter()
            self._ttft.append(req._t_first - req._t_submit)
        req.tokens.extend(toks)
        self._m["tokens_out"] += len(toks)
        if len(req.tokens) >= req.max_new_tokens and not req.done.is_set():
            req._t_done = time.perf_counter()
            if req._t_first is not None and len(req.tokens) > 1:
                self._tpot.append(
                    (req._t_done - req._t_first) / (len(req.tokens) - 1)
                )
            req.done.set()

    def _resolve(self, entry) -> None:
        """Fetch one macro-step's (or legacy chunk's) tokens — the only
        host sync, one dispatch behind the frontier — and deliver them
        to requests according to the plan. Dispatch is async, so a
        poisoned device program often surfaces HERE (at the blocking
        fetch), after the entry already left _pending — re-park it so
        _die can still reach its requests."""
        try:
            self._resolve_inner(entry)
        except Exception:
            self._pending.appendleft(entry)
            raise

    def _resolve_inner(self, entry) -> None:
        if entry[0] == "macro":
            _, toks_dev, firsts_dev, phases = entry
            toks = np.asarray(toks_dev)
            firsts = np.asarray(firsts_dev)
            for k, ph in enumerate(phases):
                for a, (_slot, req) in enumerate(ph["admissions"]):
                    self._deliver(req, [int(firsts[k, a])])
                for slot, req, take in ph["takes"]:
                    if take:
                        self._deliver(req, [int(t) for t in toks[k, :take, slot]])
            return
        _, toks_dev, takes = entry
        toks = np.asarray(toks_dev) if toks_dev is not None else None
        for slot, req, take in takes:
            if req._first_dev is not None:
                self._deliver(req, [int(np.asarray(req._first_dev))])
                req._first_dev = None
            if take and toks is not None:
                self._deliver(req, [int(t) for t in toks[slot, :take]])

    def _die(self, msg: str) -> None:
        """Fail every in-flight and queued request with a diagnostic and
        mark the engine dead so submit() raises immediately — a poisoned
        device program must not surface as N generic timeouts."""
        self._dead = msg
        doomed = set()
        for entry in self._pending:
            if entry[0] == "macro":
                for ph in entry[3]:
                    doomed.update(r for _, r in ph["admissions"])
                    doomed.update(r for _, r, _ in ph["takes"])
            else:
                doomed.update(r for _, r, _ in entry[2])
        self._pending.clear()
        doomed.update(r for r in self._slots if r is not None)
        self._slots = [None] * self.n_slots
        doomed.update(self._waiting)
        self._waiting.clear()
        while True:
            try:
                doomed.add(self._queue.get_nowait())
            except queue.Empty:
                break
        for req in doomed:
            req.error = msg
            req.done.set()

    def _loop(self) -> None:
        try:
            if self.macro_phases > 0:
                self._loop_macro()
            else:
                self._loop_chunked()
            while self._pending:  # clean shutdown: drain the frontier
                self._resolve(self._pending.popleft())
        except Exception as e:  # noqa: BLE001 — anything device-side
            msg = f"{type(e).__name__}: {e}"
            logger.exception("continuous-batching engine loop died: %s", msg)
            self._die(msg)
