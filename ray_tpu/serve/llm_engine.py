"""Continuous-batching LLM engine.

The reference's Serve LLM stack delegates the decode loop to vLLM
inside replicas (continuous batching + paged KV); there is no TPU
engine to wrap, so this is the green-field TPU-native equivalent
(SURVEY §7 step 10). Design:

- A fixed pool of KV-cache SLOTS (models/llama_decode.py per-slot
  machinery): each slot is an independent sequence at its own position.
- Decode runs in CHUNKS of C tokens as one jitted device-side lax.scan
  over ALL slots — static shapes, finished slots freeze via the
  remaining-mask (waste bounded at C-1 lanes per sequence).
- ASYNC PIPELINE: with greedy decode to a requested length, scheduling
  never depends on token VALUES — admission and eviction are planned
  from host-side counters alone. So the loop chains chunks
  device-to-device (the next chunk feeds on toks[:, -1] without a
  host fetch), dispatches admission prefills asynchronously, and
  fetches each chunk's tokens ONE CHUNK BEHIND, overlapped with the
  next chunk's compute. Over a relay-attached TPU (dispatch ~free,
  sync ~expensive) this is the difference between losing and winning
  against static batching at mixed lengths.
- ADMISSION/EVICTION at chunk boundaries: freed slots take queued
  requests immediately — short requests no longer wait for the longest
  sequence in a static batch.

Static batching (llama_decode.generate) remains the one-shot path.
Honest positioning (bench.py's llm section measures both): per decode
STEP the per-slot chunk is at parity with the static scan (~3 ms/step
measured at B=8/S=512 on v5e), and the engine's lane-efficiency win
grows with generation-length skew — but every chunk/prefill dispatch
and fetch pays the host-link fixed cost, so on a RELAY-attached chip
with a nano model the one-scan static path stays ahead; the engine's
regime is direct-attached chips and models whose step time dwarfs the
dispatch cost.
"""
from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "tokens", "done", "_first_dev",
                 "_remaining")

    def __init__(self, prompt, max_new_tokens):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.done = threading.Event()
        self._first_dev = None   # device scalar: prefill's first token
        self._remaining = 0      # host-side plan counter (decode steps owed)


class ContinuousBatchingEngine:
    def __init__(self, params, cfg, n_slots: int = 8, max_len: int = 0,
                 chunk: int = 8):
        import functools

        import jax

        from ray_tpu.models import llama_decode as D

        self._jax = jax
        self._D = D
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq_len
        self.chunk = chunk
        self.cache = D.init_slot_cache(cfg, n_slots, self.max_len)
        self._prefill_slots = jax.jit(functools.partial(D.prefill_into_slots, cfg=cfg))
        self._chunk_fn = jax.jit(
            functools.partial(D.decode_chunk_slots, chunk=chunk, cfg=cfg),
            donate_argnums=(1,),
        )
        self._slots: List[Optional[_Request]] = [None] * n_slots
        import jax.numpy as jnp

        self._next_dev = jnp.zeros(n_slots, jnp.int32)  # device-side feed tokens
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._wake = threading.Event()
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public
    def submit(self, prompt: List[int], max_new_tokens: int) -> _Request:
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt)}+{max_new_tokens}) exceeds "
                f"engine max_len {self.max_len}"
            )
        req = _Request([int(t) for t in prompt], max_new_tokens)
        self._queue.put(req)
        self._wake.set()
        return req

    def generate(self, prompt: List[int], max_new_tokens: int,
                 timeout: float = 120.0) -> List[int]:
        req = self.submit(prompt, max_new_tokens)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        return req.tokens

    def shutdown(self):
        self._running = False
        self._wake.set()
        self._thread.join(timeout=10)

    # ------------------------------------------------------------ engine
    @staticmethod
    def _bucket(n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return b

    def _admit(self) -> None:
        """Move queued requests into free slots. Admissions are BATCHED:
        requests bucket by power-of-two padded prompt length and each
        bucket prefills in ONE dispatch (prefill_into_slots) — over a
        relay-attached TPU a dispatch costs ~100x its compute, so
        per-sequence prefills would dominate the whole engine."""
        import jax.numpy as jnp

        free = [i for i, r in enumerate(self._slots) if r is None]
        batch: List[tuple] = []
        while free and not self._queue.empty():
            batch.append((free.pop(0), self._queue.get()))
        if not batch:
            return
        buckets: Dict[int, List[tuple]] = {}
        for slot, req in batch:
            buckets.setdefault(self._bucket(len(req.prompt)), []).append((slot, req))
        for tb, members in buckets.items():
            prompts = np.zeros((len(members), tb), np.int32)
            lengths = np.zeros(len(members), np.int32)
            slots = np.zeros(len(members), np.int32)
            for n, (slot, req) in enumerate(members):
                prompts[n, : len(req.prompt)] = req.prompt
                lengths[n] = len(req.prompt)
                slots[n] = slot
            firsts, self.cache = self._prefill_slots(
                self.params, jnp.asarray(prompts), jnp.asarray(lengths),
                jnp.asarray(slots), self.cache,
            )
            rem_updates = np.zeros(len(members), np.int32)
            for n, (slot, req) in enumerate(members):
                req._first_dev = firsts[n]
                req._remaining = req.max_new_tokens - 1
                rem_updates[n] = req._remaining
                self._slots[slot] = req
            self.cache["remaining"] = self.cache["remaining"].at[
                jnp.asarray(slots)
            ].set(jnp.asarray(rem_updates))
            live = [n for n, (_s, r) in enumerate(members) if r._remaining > 0]
            if live:
                idx = jnp.asarray(slots[live])
                self._next_dev = self._next_dev.at[idx].set(firsts[jnp.asarray(live)])

    def _resolve(self, entry) -> None:
        """Fetch one chunk's tokens (the only host sync, one chunk
        behind the dispatch frontier) and deliver them to requests."""
        toks_dev, takes = entry
        toks = np.asarray(toks_dev) if toks_dev is not None else None
        for slot, req, take in takes:
            if req._first_dev is not None:
                req.tokens.append(int(np.asarray(req._first_dev)))
                req._first_dev = None
            if take and toks is not None:
                req.tokens.extend(int(t) for t in toks[slot, :take])
            if len(req.tokens) >= req.max_new_tokens:
                req.done.set()

    def _loop(self) -> None:
        pending: deque = deque()  # fetch frontier: (device toks, takes)
        while self._running:
            self._admit()
            active = [(s, r) for s, r in enumerate(self._slots) if r is not None]
            if not active:
                while pending:
                    self._resolve(pending.popleft())
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            # prefill-only requests resolve without a decode chunk
            takes = []
            for slot, req in active:
                if req._remaining == 0:
                    takes.append((slot, req, 0))
                    self._slots[slot] = None
            if len(takes) == len(active):
                pending.append((None, takes))
                continue
            # dispatch the next chunk fed from device-side tokens (no sync)
            toks_dev, self.cache = self._chunk_fn(self.params, self.cache, self._next_dev)
            self._next_dev = toks_dev[:, -1]
            # deterministic bookkeeping: plan takes + evictions from
            # host counters — token values never gate scheduling
            for slot, req in active:
                if req._remaining == 0:
                    continue
                take = min(req._remaining, self.chunk)
                req._remaining -= take
                takes.append((slot, req, take))
                if req._remaining == 0:
                    self._slots[slot] = None  # evict: freed for next admit
            pending.append((toks_dev, takes))
            # fetch one chunk BEHIND: overlaps the chunk just dispatched
            while len(pending) > 1:
                self._resolve(pending.popleft())
        while pending:
            self._resolve(pending.popleft())
