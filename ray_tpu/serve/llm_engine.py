"""Continuous-batching LLM engine with macro-step scheduling.

The reference's Serve LLM stack delegates the decode loop to vLLM
inside replicas (continuous batching + paged KV); there is no TPU
engine to wrap, so this is the green-field TPU-native equivalent
(SURVEY §7 step 10). Design:

- A fixed pool of KV-cache SLOTS (models/llama_decode.py per-slot
  machinery): each slot is an independent sequence at its own position.
- PAGED KV (paged=True, the serving default path): KV memory is a
  global pool of fixed-size blocks instead of slots x max_len stripes —
  a host-side BlockAllocator (serve/_internal/kv_blocks.py) plans
  refcounted per-slot block tables that ride each dispatch as i32
  program arguments, a radix prefix cache
  (serve/_internal/prefix_cache.py) lets admissions that share a
  committed prompt prefix reuse its blocks and prefill only the
  suffix, and REAL SAMPLING (temperature/top-k/top-p, per-request
  seeds, device-side stop-token detection) runs inside the decode scan.
- PLAN-AND-REPAIR replaces the old greedy-only invariant: with
  sampling, token values CAN end a sequence early (stop tokens), so
  the host keeps planning K phases ahead speculatively from counters,
  the device zeroes a stopped slot's `remaining` the moment it samples
  a stop, and the host repairs its plan when the resolved tokens
  reveal it — truncating delivery at the stop, freeing the slot and
  its blocks at the next plan boundary, and billing the discarded
  planned steps as `plan_repair_waste_pct` (alias
  `speculative_waste_pct`). Block reuse under
  speculation is safe by construction: tables are PER-DISPATCH host
  plans, so a zombie lane (stopped or cancelled but still riding
  already-planned phases) only ever writes blocks it owned at dispatch
  time — every later dispatch points it at the null block, and a new
  owner's admission prefill (always a later dispatch, device programs
  serialize) overwrites before any read.
- KEY INVARIANT (greedy requests — and the legacy dense mode's only
  mode): greedy decode to a requested length means scheduling never
  depends on token VALUES — admission, eviction and chunk sizing are
  all decidable from host-side counters alone; a stop-free plan needs
  zero repair.
- MACRO-STEP SCHEDULING exploits that invariant to collapse dispatch
  count: the host plans K phases of admissions/evictions ahead, then
  executes the WHOLE plan as one jitted dispatch
  (llama_decode.macro_step_slots — a lax.scan over the plan whose
  phases run a fused admission prefill + a decode chunk device-side).
  Prompts ride along as program arguments, so admission costs zero
  extra dispatches.
- ADAPTIVE CHUNKS: each phase decodes exactly to the next scheduling
  event — min(chunk, min remaining over live slots) — so a freed slot
  is re-admitted at the very next phase instead of idling to a fixed
  chunk boundary; phases beyond their planned steps are skipped via
  lax.cond, so a shrunk phase costs only its real steps.
- ASYNC PIPELINE: tokens are fetched ONE MACRO-STEP BEHIND the
  dispatch frontier — while macro-step N executes, the host plans and
  dispatches N+1 from counters, then resolves N's tokens overlapped
  with N+1's compute.

Dispatch-cost math (why macro-stepping wins): with per-chunk
dispatching, serving G tokens through B slots at chunk C costs
~G/(B*C) chunk dispatches + one prefill dispatch per admission bucket;
every dispatch pays the host-link fixed cost D, so relay-attached
chips (D >> step time) lose to static batching's one-scan-per-group
even though continuous batching wastes far fewer lanes at mixed
lengths (round-5 bench: 0.31x). Macro-stepping divides the chunk
dispatches by K and folds the prefill dispatches into the same
program, so total dispatch overhead drops ~K*(1 + prefills/chunks)x —
an order of magnitude at K=8 — while the lane-efficiency win of
iteration-level scheduling is kept (and sharpened by adaptive chunks).
`metrics()` reports dispatches/token, lane occupancy and TTFT/TPOT
percentiles so bench.py can track the regime per round.

Static batching (llama_decode.generate) remains the one-shot path; the
legacy per-chunk loop survives behind macro_phases=0 for A/B testing.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.observability import flight_recorder as _flightrec
from ray_tpu.observability import lifeline as _lifeline
from ray_tpu.util.metrics import metric_singletons as _metric_singletons

logger = logging.getLogger(__name__)

# flight-recorder event id resolved once: the per-dispatch ring write
# must be a constant-arg call (lint-pinned — no dict lookup, no
# allocation on the dispatch path)
_EV_DISPATCH = _flightrec.EV["dispatch"]

# latency histogram boundaries (seconds): wide enough for relay-attached
# chips (TTFT can run seconds) and fine enough near the fast end for
# meaningful p50 interpolation
_TTFT_BOUNDS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)
_TPOT_BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5)


def _engine_metrics_factory():
    """Process-wide serving metrics, tagged per engine — a singleton
    group because the metrics registry keeps every constructed Metric
    (two engines must not double-register the same name)."""
    from ray_tpu.util import metrics

    return dict(
        ttft=metrics.Histogram(
            "ray_tpu_llm_ttft_s", "time to first token",
            boundaries=_TTFT_BOUNDS, tag_keys=("engine",)),
        tpot=metrics.Histogram(
            "ray_tpu_llm_tpot_s", "time per output token",
            boundaries=_TPOT_BOUNDS, tag_keys=("engine",)),
        tokens=metrics.Counter(
            "ray_tpu_llm_tokens_out_total", "tokens delivered",
            tag_keys=("engine",)),
        dispatches=metrics.Counter(
            "ray_tpu_llm_dispatches_total", "device dispatches",
            tag_keys=("engine",)),
        dpt=metrics.Gauge(
            "ray_tpu_llm_dispatches_per_token",
            "dispatch amortization", tag_keys=("engine",)),
        occupancy=metrics.Gauge(
            "ray_tpu_llm_lane_occupancy_pct",
            "useful slot-steps / total slot-steps", tag_keys=("engine",)),
        migration=metrics.Histogram(
            "ray_tpu_llm_migration_s",
            "prefill->decode KV handoff latency",
            boundaries=_TTFT_BOUNDS, tag_keys=("engine",)),
    )


_engine_metrics = _metric_singletons(_engine_metrics_factory)


class _LatencyHist:
    """Engine-local latency histogram, mirrored into the shared
    Prometheus Histogram. The engine loop thread appends while metrics()
    reads — all mutation under one lock, so the percentile snapshot is
    consistent by construction (the PR 2 deque fix, structurally).

    Percentiles stay RECENT-weighted on a long-lived replica (the
    invariant the PR 2 deque carried): bucket counts rotate through two
    epochs of `epoch` observations each, and percentiles read the last
    epoch–2·epoch samples — so a latency regression moves p95 within
    ~epoch requests instead of needing to outvote the process's whole
    history. The shared Prometheus histogram stays cumulative (series
    math like rate() expects monotonic counters); resettable
    (reset_metrics between bench passes)."""

    def __init__(self, bounds, shared_hist, tags, epoch: int = 2048):
        import bisect

        self._bisect = bisect.bisect_left
        self.bounds = list(bounds)
        self._epoch = epoch
        self._counts = [0] * (len(self.bounds) + 1)   # current epoch
        self._prev = [0] * (len(self.bounds) + 1)     # previous epoch
        self._n = 0       # observations in the current epoch
        self._n_prev = 0
        self._sum = 0.0   # current-epoch sum (rotates with the counts)
        self._lock = threading.Lock()
        self._shared = shared_hist
        self._tags = tags

    def observe(self, v: float) -> None:
        with self._lock:
            if self._n >= self._epoch:
                self._prev, self._counts = (
                    self._counts, [0] * (len(self.bounds) + 1))
                self._n_prev, self._n = self._n, 0
                self._sum = 0.0
            self._counts[self._bisect(self.bounds, v)] += 1
            self._sum += v
            self._n += 1
        try:
            self._shared.observe(v, tags=self._tags)
        except Exception:
            pass

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._prev = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._n = 0
            self._n_prev = 0

    def percentiles_ms(self, qs=(0.50, 0.95, 0.99)) -> List[Optional[float]]:
        """Prometheus-style interpolation inside the target bucket over
        the rotating window (previous + current epoch); the +Inf bucket
        clamps to the last finite boundary."""
        with self._lock:
            counts = [p + c for p, c in zip(self._prev, self._counts)]
            n = self._n_prev + self._n
        if n == 0:
            return [None] * len(qs)
        out = []
        for q in qs:
            rank = q * n
            cum = 0
            val = self.bounds[-1]
            for i, c in enumerate(counts):
                prev_cum = cum
                cum += c
                if cum >= rank and c > 0:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
                    val = lo + (hi - lo) * ((rank - prev_cum) / c)
                    break
            out.append(round(val * 1e3, 3))
        return out


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "tokens", "done", "error",
                 "exc", "on_done", "sampling", "finish_reason", "_first_dev",
                 "_remaining", "_rounds_est", "_rounds_inflight",
                 "_t_submit", "_t_first", "_t_done",
                 "_trace_ctx", "_start", "_blocks", "_blocks_freed",
                 "_done_lock", "rid", "_rid_b", "_migrate", "export",
                 "_resume", "_qtok")

    def __init__(self, prompt, max_new_tokens, on_done=None, sampling=None,
                 rid: Optional[str] = None):
        from ray_tpu.serve._internal.sampling import SamplingParams

        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling or SamplingParams()
        # caller-generated request id (redispatch bookkeeping + logs);
        # _rid_b is the pre-encoded flight-recorder form — encoded ONCE
        # here so no per-event path ever pays the str→bytes conversion
        self.rid = rid
        self._rid_b = _lifeline.rid_bytes(rid) if rid else b""
        # "length" | "stop" | "cancelled" | None (error/unfinished)
        self.finish_reason: Optional[str] = None
        self.tokens: List[int] = []
        self.done = threading.Event()
        # completion is a cross-thread event (engine loop delivers,
        # caller threads cancel): _finish's test-and-set runs under this
        self._done_lock = threading.Lock()
        self._start = 0            # reused-prefix tokens (paged admissions)
        self._blocks: List[int] = []   # KV blocks owned (paged mode)
        self._blocks_freed = False
        # completion callback, fired (once) from the engine loop thread
        # right after done.set() — the serve direct-transport path
        # completes the caller's deferred reply here with one ring
        # write, instead of parking a replica thread per request on the
        # event (see _LLMServer.__call__)
        self.on_done = on_done
        self.error: Optional[str] = None
        # typed failure (serve/errors.py) — what generate()/the deferred
        # completion raise so the taxonomy survives the process boundary
        # (error stays the human-readable string form)
        self.exc: Optional[BaseException] = None
        self._first_dev = None   # device scalar: prefill's first token (legacy path)
        self._remaining = 0      # host-side plan counter (decode steps owed)
        # KV-plane state: _migrate marks a prefill-pool request that
        # hands off after its first token; export holds the exporter's
        # {ref, ...} handoff metadata (keeps the ObjectRef alive until
        # the decode side's reply lands); _resume carries an inbound
        # migration's fetched payload until the import admits it
        self._migrate = False
        self.export: Optional[Dict[str, Any]] = None
        self._resume: Optional[Dict[str, Any]] = None
        self._qtok = 0           # queued-prefill-token accounting (idempotent)
        # speculative mode: acceptance is data-dependent, so the planner
        # schedules verify ROUNDS from an estimate instead of exact
        # steps — rounds still plannable / already dispatched-unresolved
        self._rounds_est = 0
        self._rounds_inflight = 0
        self._t_submit = time.perf_counter()
        self._t_first: Optional[float] = None
        self._t_done: Optional[float] = None
        # trace context captured on the SUBMITTING thread (the engine
        # loop runs in its own thread, where the contextvar is unset):
        # the dispatches this request rides parent under it, so a slow
        # serve request is followable proxy span → replica task → the
        # exact macro-steps that decoded it
        self._trace_ctx: Optional[Dict[str, str]] = None


def _finish(req: "_Request", error: Optional[str] = None,
            reason: Optional[str] = None,
            exc: Optional[BaseException] = None) -> bool:
    """Complete a request ATOMICALLY: exactly one caller wins (the
    engine loop delivering vs. a caller thread cancelling race here),
    the final error/finish_reason are written before `done` is visible,
    and on_done fires exactly once, outside the lock (callback failures
    are logged, never poison the engine loop). `exc` carries the typed
    failure (shed / deadline / replica-death) alongside the string form.
    Returns True for the winner, False if the request was already
    complete."""
    with req._done_lock:
        if req.done.is_set():
            return False
        if exc is not None:
            req.exc = exc
            if error is None:
                error = str(exc)
        if error is not None:
            req.error = error
        if reason is not None:
            req.finish_reason = reason
        cb = req.on_done
        req.on_done = None
        req.done.set()
    if cb is not None:
        try:
            cb(req)
        except Exception:
            logger.exception("llm request on_done callback failed")
    return True


class ContinuousBatchingEngine:
    def __init__(self, params, cfg, n_slots: int = 8, max_len: int = 0,
                 chunk: int = 8, macro_phases: int = 8, name: str = "default",
                 paged: bool = False, block_size: int = 16,
                 n_blocks: int = 0, prefix_cache: bool = True,
                 max_queue: Optional[int] = None, draft_model=None,
                 num_speculative_tokens: int = 0,
                 role: Optional[str] = None,
                 cluster_cache: Optional[bool] = None,
                 digest_prefix_len: int = 32):
        import jax

        from ray_tpu.models import llama_decode as D

        if role not in (None, "prefill", "decode"):
            raise ValueError(
                f"engine role must be None, 'prefill' or 'decode', got "
                f"{role!r}")
        if role is not None and not paged:
            raise ValueError(
                "disaggregated pool roles require the paged engine "
                "(paged=True) — KV migration is block-granular")
        self.role = role

        self._jax = jax
        self._D = D
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq_len
        self.chunk = chunk
        self.macro_phases = macro_phases  # 0 => legacy per-chunk dispatching
        self.paged = bool(paged)
        self._alloc = None
        self._prefix = None
        if self.paged:
            if macro_phases < 1:
                raise ValueError("paged KV requires macro_phases >= 1")
            if block_size & (block_size - 1) or block_size < 1:
                raise ValueError(f"block_size must be a power of two, got {block_size}")
            from ray_tpu.serve._internal.kv_blocks import BlockAllocator
            from ray_tpu.serve._internal.prefix_cache import RadixPrefixCache

            self.block_size = block_size
            # table width: blocks to cover max_len (per-slot ceiling)
            self._mb = -(-self.max_len // block_size)
            # default pool: same KV budget as the dense slots x max_len
            # cache (+1 for the reserved null block) — paged wins by
            # serving MORE slots from the SAME budget, not more memory
            self.n_blocks = n_blocks or n_slots * self._mb + 1
            self._alloc = BlockAllocator(self.n_blocks, block_size)
            if prefix_cache:
                self._prefix = RadixPrefixCache(self._alloc)
            self.cache = D.init_paged_cache(cfg, n_slots, self.n_blocks,
                                            block_size)
            # greedy variant prebound; the sampled twin resolves lazily
            # at the first plan that actually contains a sampled request
            # (two static variants — all-greedy traffic must not pay the
            # per-step sort/softmax/rng sampling pipeline)
            self._macro_paged_fn = D.jitted_macro_step_slots_paged(
                cfg, chunk, sampled=False)
        else:
            self.cache = D.init_slot_cache(cfg, n_slots, self.max_len)
        # draft-model speculative decoding (paged-only): the spec macro
        # program is a THIRD static variant family beside the PR-7
        # greedy/sampled pair — with speculation off these attributes
        # stay None and the engine never traces a program containing a
        # single draft parameter (lint-enforced)
        self.n_spec = int(num_speculative_tokens)
        self.draft_params = None
        self.draft_cfg = None
        self.draft_cache = None
        if draft_model is not None:
            if not self.paged:
                raise ValueError(
                    "speculative decoding requires the paged engine "
                    "(paged=True)")
            if self.n_spec < 1:
                raise ValueError(
                    "draft_model requires num_speculative_tokens >= 1, "
                    f"got {self.n_spec}")
            from ray_tpu.serve._internal.speculative import resolve_draft_model

            self.draft_params, self.draft_cfg = resolve_draft_model(
                draft_model, params, cfg)
            if self.draft_params is params:
                # "self"-drafting: draft weights ARE the target weights,
                # so draft and verify writes are bit-identical and ONE
                # pool serves both models — draft_cache stays None (the
                # kernels' shared-pool mode): no mirror prefill at
                # admission, no second pool's memory, no hole tracking
                self.draft_cache = None
            else:
                # the draft pool mirrors the target's block geometry:
                # one host allocator plan addresses both pools
                self.draft_cache = D.init_spec_cache(
                    self.draft_cfg, n_slots, self.n_blocks, block_size)
            # acceptance EMA feeding the round planner: start optimistic
            # (full acceptance) so the first plans don't over-schedule —
            # resyncs against observed accepted lengths at resolution
            self._accept_ema = float(self.n_spec + 1)
        elif self.n_spec > 0:
            raise ValueError(
                "num_speculative_tokens > 0 requires a draft_model")
        if role is not None and self.draft_cache is not None:
            # a SEPARATE draft pool cannot follow a migration (only the
            # target pool's blocks ship) — the resumed request's draft
            # lane would verify against garbage. Shared-pool
            # self-drafting (draft_cache None) migrates fine.
            raise ValueError(
                "disaggregated pools require a shared-pool draft model "
                "(separate draft KV cannot migrate across replicas)")
        # memoized per (cfg, chunk): same-geometry engines share one jit
        # wrapper, so engine construction never recompiles warm programs
        self._prefill_slots = D.jitted_prefill_into_slots(cfg)
        self._chunk_fn = D.jitted_decode_chunk_slots(cfg, chunk)
        self._macro_fn = D.jitted_macro_step_slots(cfg, chunk)
        self._slots: List[Optional[_Request]] = [None] * n_slots
        import jax.numpy as jnp

        self._next_dev = jnp.zeros(n_slots, jnp.int32)  # device-side feed tokens
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._waiting: deque = deque()       # planner-side FIFO (loop thread only)
        self._pending: deque = deque()       # fetch frontier: tagged entries
        # KV-plane plumbing: inbound migrations (fetched payloads
        # awaiting a slot), cross-thread jobs the loop executes at plan
        # boundaries (allocator/trie mutation stays loop-thread-only),
        # and the queued-prefill-token gauge feeding the prefill pool's
        # autoscaling signal
        self._rqueue: "queue.Queue[_Request]" = queue.Queue()
        self._resuming: deque = deque()      # loop thread only
        self._jobs: "queue.Queue" = queue.Queue()
        self._qtok_lock = threading.Lock()
        self._queued_prefill_tokens = 0
        self._kv_inv = None
        if self.paged and self._prefix is not None:
            from ray_tpu.serve._internal.kv_plane import (
                PrefixInventory, cluster_cache_enabled)

            self._cluster_cache = cluster_cache_enabled(cluster_cache)
            if self._cluster_cache:
                self._kv_inv = PrefixInventory(digest_prefix_len)
        else:
            self._cluster_cache = False
        self._dead: Optional[str] = None
        # admission bound: max requests WAITING (beyond the resident
        # slots) before submit() sheds with a typed 503-shaped error —
        # overload must become fast rejections, not a timeout pileup.
        # 0 = unbounded (the library default; serve deployments set it)
        import os as _os

        if max_queue is None:
            max_queue = int(_os.environ.get("RAY_TPU_SERVE_MAX_QUEUE", "0"))
        self.max_queue = max(0, int(max_queue))
        # EMA of completed-request service time (submit → done): the
        # admission ETA estimate. Written by the loop thread at
        # delivery, read by submit() — a torn float read is harmless
        self._ema_service_s = 0.0
        # serving metrics (monotonic counters + latency histograms).
        # _m_lock makes RELATED counters a consistent snapshot: the
        # migration/prefix-export sites bump several counters per event,
        # and metrics() copies the dict under the same lock so a
        # mid-burst scrape can't return torn totals (migrations_out
        # without its migrated_blocks_out). Single-counter bumps on the
        # loop thread stay lock-free — a lone counter can't tear.
        self.name = name
        self._m_lock = threading.Lock()
        # per-process crash ring: per-dispatch events land here with ONE
        # ring write (no allocation, no pickle, no RPC — lint-pinned)
        self._fr = _flightrec.get_recorder()
        self._m = {"dispatches": 0, "tokens_out": 0, "slot_steps": 0,
                   "useful_slot_steps": 0, "wasted_steps": 0,
                   "prefill_tokens": 0, "reused_prefix_tokens": 0,
                   "kv_blocks_peak_in_use": 0, "shed_queue_full": 0,
                   "shed_eta": 0, "deadline_expired": 0,
                   "spec_verify_rounds": 0, "draft_proposed_tokens": 0,
                   "draft_accepted_tokens": 0, "migrations_out": 0,
                   "migrations_in": 0, "migrated_blocks_out": 0,
                   "migrated_blocks_in": 0, "prefix_exports": 0,
                   "prefix_imports": 0, "requests_completed": 0}
        shared = _engine_metrics()
        self._tags = {"engine": name}
        self._ttft = _LatencyHist(_TTFT_BOUNDS, shared["ttft"], self._tags)
        self._tpot = _LatencyHist(_TPOT_BOUNDS, shared["tpot"], self._tags)
        self._mig = _LatencyHist(_TTFT_BOUNDS, shared["migration"], self._tags)
        # device-step telemetry for each dispatch: host dispatch slices
        # land on the unified trace's device rows, parented under the
        # trace contexts of the requests each dispatch serves
        from ray_tpu.observability import StepTelemetry, get as _get_tel

        self._tel = _get_tel(f"llm_dispatch:{name}") or StepTelemetry(
            f"llm_dispatch:{name}", kind="serve")
        self._jit_cache_sizes: Dict[int, int] = {}
        self._t_snapshot = 0.0
        self._pub_marker: Optional[tuple] = None
        self._wake = threading.Event()
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public
    def eta_s(self) -> float:
        """Admission ETA estimate: how long a request submitted NOW is
        expected to wait+run, from the queue depth and the service-time
        EMA. 0.0 until the first completion (no data, no shedding)."""
        ema = self._ema_service_s
        if ema <= 0.0:
            return 0.0
        waiting = self._queue.qsize() + len(self._waiting)
        return (waiting / max(1, self.n_slots)) * ema + ema

    def _check_admission(self, sampling) -> None:
        """Deadline/overload admission control — the typed-503 gate.
        Raises; on the happy path costs two counter reads."""
        from ray_tpu.serve.errors import DeadlineExceededError, RequestShedError

        now = time.time()
        deadline = sampling.deadline
        if deadline is not None and deadline <= now:
            self._m["deadline_expired"] += 1
            raise DeadlineExceededError(
                f"deadline passed {now - deadline:.2f}s before admission"
            )
        if self.max_queue:
            waiting = self._queue.qsize() + len(self._waiting)
            if waiting >= self.max_queue:
                self._m["shed_queue_full"] += 1
                raise RequestShedError(
                    f"admission queue full ({waiting} waiting >= "
                    f"max_queue {self.max_queue})",
                    retry_after_s=max(0.1, round(self.eta_s(), 2)),
                )
        if deadline is not None:
            eta = self.eta_s()
            if eta > 0.0 and now + eta > deadline:
                self._m["shed_eta"] += 1
                raise RequestShedError(
                    f"queue ETA {eta:.2f}s overruns the request deadline "
                    f"({deadline - now:.2f}s away) — shedding instead of "
                    f"queueing a guaranteed miss",
                    retry_after_s=max(0.1, round(eta, 2)),
                )

    def submit(self, prompt: List[int], max_new_tokens: int,
               on_done=None, sampling=None, rid: Optional[str] = None) -> _Request:
        from ray_tpu.serve._internal.sampling import SamplingParams

        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        if len(prompt) == 0:
            # length 0 is the macro plan's padding-row sentinel (and the
            # legacy prefill's last-position logits would be garbage)
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt)}+{max_new_tokens}) exceeds "
                f"engine max_len {self.max_len}"
            )
        sampling = SamplingParams.from_request(sampling)
        if not sampling.greedy and sampling.seed is None:
            # seedless sampled requests draw fresh entropy: two users
            # omitting the seed must not share a token stream (an
            # explicit seed — including 0 — stays fully reproducible)
            import dataclasses as _dc
            import os as _os

            sampling = _dc.replace(
                sampling, seed=int.from_bytes(_os.urandom(4), "little"))
        if not self.paged and (not sampling.greedy or sampling.stop):
            # dense mode has no device-side sampling/stop detection —
            # its macro program is the greedy-invariant one
            raise ValueError(
                "temperature sampling and stop tokens require the paged "
                "engine (paged=True)"
            )
        # prefill-pool requests hand off after their first token, so
        # they reserve blocks for the PROMPT only (admission writes
        # prompt positions; the decode pool reserves the full span)
        will_migrate = self.role == "prefill" and max_new_tokens > 1
        if self.paged:
            span = len(prompt) if will_migrate else len(prompt) + max_new_tokens
            need = self._alloc.blocks_for_tokens(span)
            if need > self.n_blocks - 1:
                raise ValueError(
                    f"request needs {need} KV blocks, pool only has "
                    f"{self.n_blocks - 1}"
                )
        try:
            self._check_admission(sampling)
        except Exception as e:
            if rid:
                _lifeline.record(rid, "shed", engine=self.name,
                                 reason=type(e).__name__)
            raise
        req = _Request([int(t) for t in prompt], max_new_tokens,
                       on_done=on_done, sampling=sampling, rid=rid)
        req._migrate = will_migrate
        req._qtok = len(req.prompt)
        with self._qtok_lock:
            self._queued_prefill_tokens += req._qtok
        try:
            from ray_tpu.util import tracing

            req._trace_ctx = tracing.current_context()
        except Exception:
            pass
        if rid:
            _lifeline.record(rid, "submit", ctx=req._trace_ctx,
                             rid_b=req._rid_b, engine=self.name,
                             prompt_tokens=len(req.prompt),
                             max_new_tokens=max_new_tokens,
                             migrate=will_migrate,
                             a=float(len(req.prompt)))
        self._queue.put(req)
        if self._dead is not None:
            # lost the race with the loop dying: the dead loop will never
            # drain the queue, so fail the request here instead of letting
            # the caller eat a generic timeout
            msg = f"engine is dead: {self._dead}"
            _finish(req, error=msg)
            raise RuntimeError(msg)
        self._wake.set()
        return req

    def generate(self, prompt: List[int], max_new_tokens: int,
                 timeout: float = 120.0, sampling=None,
                 rid: Optional[str] = None) -> List[int]:
        req = self.submit(prompt, max_new_tokens, sampling=sampling, rid=rid)
        if not req.done.wait(timeout):
            # CANCEL, don't abandon: a timed-out request left live would
            # keep burning decode steps and (paged) holding KV blocks
            # forever — cancellation frees the slot and its blocks at
            # the engine's next plan boundary
            self.cancel(req, "cancelled: generation timed out")
            raise TimeoutError("generation timed out (request cancelled)")
        if req.error is not None:
            if req.exc is not None:
                # typed failure (shed / deadline / replica-death):
                # propagate the class, not a stringly RuntimeError — the
                # handle's redispatch policy and the proxy's HTTP
                # mapping both classify by isinstance
                raise req.exc
            raise RuntimeError(f"generation failed: {req.error}")
        return req.tokens

    def cancel(self, req: _Request, msg: str = "cancelled") -> None:
        """Cancel an in-flight request (idempotent, any thread). The
        request completes immediately with `error=msg`; the engine loop
        reclaims its slot and KV blocks at the next plan boundary
        (_repair). Device lanes it still rides in already-dispatched
        plans emit discarded tokens, billed as speculative waste. A
        cancel racing normal delivery loses cleanly: _finish's atomic
        test-and-set makes whoever gets there first the sole completer."""
        if _finish(req, error=msg, reason="cancelled"):
            if req.rid:
                _lifeline.record(req.rid, "finish", ctx=req._trace_ctx,
                                 rid_b=req._rid_b, engine=self.name,
                                 reason="cancelled",
                                 tokens=len(req.tokens))
                _lifeline.finish(req.rid)
            self._wake.set()

    def shutdown(self):
        self._running = False
        self._wake.set()
        self._thread.join(timeout=10)
        if self._dead is None and not self._thread.is_alive():
            # final drain: the loop can exit between the _resolve that
            # completed a request and the _repair that frees its slot
            # and KV blocks (the ONLY freeing path in spec mode, which
            # never evicts at plan time) — run it here, single-threaded
            # now, so shutdown leaves allocator refs == radix-cache refs
            self._repair()

    def load(self) -> int:
        """Resident + queued request count — the autoscaling load
        signal a Replica publishes through the telemetry path. Counter
        reads only (the slot list and wait queue belong to the loop
        thread; a momentarily torn read just shifts one load sample)."""
        return (
            self._queue.qsize()
            + len(self._waiting)
            + self._rqueue.qsize()
            + len(self._resuming)
            + sum(1 for s in self._slots if s is not None)
        )

    # ------------------------------------------------------- KV plane
    def _dec_qtok(self, req: _Request) -> None:
        """Retire a request's queued-prefill-token contribution
        (idempotent — admission, shedding and death can race only in
        program order on the loop thread, but belt and braces)."""
        n, req._qtok = req._qtok, 0
        if n:
            with self._qtok_lock:
                self._queued_prefill_tokens -= n

    def pool_signals(self) -> Dict[str, Any]:
        """The per-pool autoscaling signals (ISSUE 18): queued prefill
        tokens for the prefill pool (work not yet admitted — slot-count
        load signals under-weigh long prompts), decode lane occupancy
        for the decode pool (resident + inbound migrations). Counter
        reads only; published by the Replica stat reporter."""
        with self._qtok_lock:
            qtok = self._queued_prefill_tokens
        resumes = self._rqueue.qsize() + len(self._resuming)
        return {
            "pool": self.role,
            "queued_prefill_tokens": max(0, qtok),
            "decode_lanes_busy":
                sum(1 for s in self._slots if s is not None) + resumes,
            "resume_queue": resumes,
        }

    def kv_inventory(self) -> List[str]:
        """Digest list of locally committed prompt prefixes — the
        replica's contribution to the cluster-wide cache inventory
        (JSON-safe, atomic snapshot)."""
        return self._kv_inv.published() if self._kv_inv is not None else []

    def has_local_prefix(self, digest) -> bool:
        return self._kv_inv is not None and digest in self._kv_inv

    def _register_prefix(self, prompt: List[int]) -> None:
        """Record a radix-committed prefix in the publishable inventory
        (loop thread, right after the trie insert)."""
        if self._kv_inv is None:
            return
        n_committed = (len(prompt) // self.block_size) * self.block_size
        self._kv_inv.register(prompt, n_committed)

    def call_on_loop(self, fn, timeout: float = 30.0):
        """Run `fn` on the engine loop thread (the only thread allowed
        to touch the allocator, the radix trie and the cache handle) and
        return its result. Blocks the CALLER, never the loop."""
        import concurrent.futures

        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        self._jobs.put((fn, fut))
        self._wake.set()
        return fut.result(timeout)

    def _drain_jobs(self) -> None:
        while True:
            try:
                fn, fut = self._jobs.get_nowait()
            except queue.Empty:
                return
            try:
                fut.set_result(fn())
            except Exception as e:  # noqa: BLE001 — job errors go to the caller
                fut.set_exception(e)

    def submit_resumed(self, prompt: List[int], first_token: int,
                       max_new_tokens: int, k, v, n_data_blocks: int,
                       on_done=None, sampling=None, rid: Optional[str] = None,
                       t_export: Optional[float] = None) -> _Request:
        """Admit a MIGRATED request: the prompt was prefilled (and its
        first token sampled) on a prefill-pool replica; `k`/`v` are its
        gathered KV block slices fetched from the object plane (padded
        to the exporter's bucket). The request joins the resume queue
        and the loop imports it at the next plan boundary — no admission
        control (it already paid admission at the prefill pool; shedding
        mid-migration would discard finished prefill work)."""
        from ray_tpu.serve._internal.sampling import SamplingParams

        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        if not self.paged:
            raise ValueError("KV resume requires the paged engine")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt)}+{max_new_tokens}) exceeds "
                f"engine max_len {self.max_len}")
        need = self._alloc.blocks_for_tokens(len(prompt) + max_new_tokens)
        if need > self.n_blocks - 1:
            raise ValueError(
                f"resumed request needs {need} KV blocks, pool only has "
                f"{self.n_blocks - 1}")
        sampling = SamplingParams.from_request(sampling)
        req = _Request([int(t) for t in prompt], max_new_tokens,
                       on_done=on_done, sampling=sampling, rid=rid)
        req._resume = {"k": k, "v": v, "n_data": int(n_data_blocks),
                       "first": int(first_token), "t_export": t_export}
        try:
            from ray_tpu.util import tracing

            req._trace_ctx = tracing.current_context()
        except Exception:
            pass
        if rid:
            _lifeline.record(rid, "resume_submit", ctx=req._trace_ctx,
                             rid_b=req._rid_b, engine=self.name,
                             blocks=int(n_data_blocks),
                             a=float(n_data_blocks))
        self._rqueue.put(req)
        if self._dead is not None:
            msg = f"engine is dead: {self._dead}"
            _finish(req, error=msg)
            raise RuntimeError(msg)
        self._wake.set()
        return req

    def export_prefix(self, digest) -> Optional[Dict[str, Any]]:
        """Cluster prefix-cache export: look `digest` up in the local
        inventory, gather its committed blocks (dispatched on the loop
        thread, BEFORE any later mutation can recycle them — device
        programs serialize) and publish ONE object-plane put (this
        thread: serialization syncs on the gather, off the loop).
        Returns the handoff dict (tokens + hex ref + a live "_ref" the
        caller must hold until importers are done) or None on miss."""
        from ray_tpu.serve._internal import kv_plane

        def job():
            if self._kv_inv is None:
                return None
            tokens = self._kv_inv.tokens_for(digest)
            if tokens is None:
                return None
            blocks = self._prefix.match_blocks(tokens)
            if not blocks:
                return None
            import jax.numpy as jnp

            ids = kv_plane.pad_block_ids(blocks)
            k, v = self._D.jitted_gather_kv_blocks()(
                self.cache, jnp.asarray(ids))
            return list(tokens[: len(blocks) * self.block_size]), k, v, \
                len(blocks)

        res = self.call_on_loop(job)
        if res is None:
            return None
        tokens, k, v, n = res
        import ray_tpu

        ref = ray_tpu.put({"k": k, "v": v, "n": n})
        with self._m_lock:
            # off-loop-thread increment: without the lock a concurrent
            # metrics() copy could tear this against the loop's counters
            self._m["prefix_exports"] += 1
        return {"tokens": tokens, "ref": ref.hex(), "n_data_blocks": n,
                "block_size": self.block_size, "_ref": ref}

    def import_prefix(self, tokens: List[int], k, v,
                      n_data_blocks: int) -> int:
        """Cluster prefix-cache import: scatter a peer's committed
        prefix blocks into the local pool and commit them to the radix
        trie, so later admissions here reuse a prefix prefilled on
        ANOTHER replica. Opportunistic — pool exhaustion drops the
        import silently (it's a cache fill, not a request). Returns
        blocks newly committed."""

        def job():
            if self._prefix is None:
                return 0
            have = self._prefix.match_blocks(tokens)
            if len(have) >= n_data_blocks:
                return 0  # already resident
            from ray_tpu.serve._internal import kv_plane
            from ray_tpu.serve._internal.kv_blocks import BlockPoolExhausted

            try:
                blocks = self._alloc.alloc(n_data_blocks)
            except BlockPoolExhausted:
                return 0
            import jax.numpy as jnp

            dst = kv_plane.pad_block_ids(blocks)
            self.cache = self._D.jitted_scatter_kv_blocks()(
                self.cache, jnp.asarray(dst), k, v)
            committed = tokens[: n_data_blocks * self.block_size]
            added = self._prefix.insert(committed, blocks)
            # hand ownership to the cache: drop the alloc refs so the
            # trie's increfs are the only pins (duplicate blocks for
            # already-present nodes free right here — leak-audit clean)
            self._alloc.decref(blocks)
            self._register_prefix(committed)
            with self._m_lock:
                self._m["prefix_imports"] += 1
                self._m["migrated_blocks_in"] += added
            return added

        return self.call_on_loop(job)

    def metrics(self) -> Dict[str, Any]:
        """Serving metrics since construction (or reset_metrics()):
        dispatch counts, dispatches/token, lane occupancy %, TTFT/TPOT
        p50/p95/p99 from the latency histograms (bucket-interpolated;
        the histogram lock makes the snapshot safe against the engine
        loop's concurrent appends). Tokens count at DELIVERY, so read
        after requests complete for exact ratios. The copy happens under
        _m_lock so multi-counter updates (migration, prefix export) are
        all-or-nothing in the snapshot — a mid-burst scrape can't see
        migrations_out without its migrated_blocks_out."""
        with self._m_lock:
            m = dict(self._m)
        m["queue_depth"] = self.load()  # live gauge, not a counter
        toks = max(1, m["tokens_out"])
        m["dispatches_per_token"] = round(m["dispatches"] / toks, 4)
        m["lane_occupancy_pct"] = round(
            100.0 * m["useful_slot_steps"] / max(1, m["slot_steps"]), 1
        )
        # plan-and-repair bill: % of PLANNED useful steps whose tokens
        # were discarded (early stop / cancellation revealed after the
        # speculative plan shipped). Historically named
        # speculative_waste_pct — kept as an alias now that draft-model
        # speculation has its own, distinct rejection metric below.
        m["plan_repair_waste_pct"] = round(
            100.0 * m["wasted_steps"] / max(1, m["useful_slot_steps"]), 2
        )
        m["speculative_waste_pct"] = m["plan_repair_waste_pct"]
        # draft-model speculation ledger: % of proposed draft tokens the
        # target rejected, and the headline win — verified tokens per
        # verify round (= accepted drafts + the correction/bonus token;
        # 1.0 would mean speculation is buying nothing)
        proposed = m["draft_proposed_tokens"]
        m["draft_rejection_pct"] = round(
            100.0 * (proposed - m["draft_accepted_tokens"]) / max(1, proposed),
            2,
        )
        rounds = m["spec_verify_rounds"]
        m["accepted_tokens_per_dispatch"] = round(
            (m["draft_accepted_tokens"] + rounds) / rounds, 3
        ) if rounds else 0.0
        # admission-control ledger: total sheds + the ETA estimate the
        # next admission would be judged against
        m["shed_requests"] = m["shed_queue_full"] + m["shed_eta"]
        m["avg_service_ms"] = round(self._ema_service_s * 1e3, 1)
        m["admission_eta_ms"] = round(self.eta_s() * 1e3, 1)
        if self.paged:
            total = self.n_blocks - 1  # block 0 is the reserved null
            m["kv_blocks_total"] = total
            m["kv_blocks_in_use"] = self._alloc.used_blocks
            # peak utilization over the workload — the snapshot of record
            # (in_use drains to the cache-pinned floor between requests)
            m["kv_blocks_utilization_pct"] = round(
                100.0 * m["kv_blocks_peak_in_use"] / max(1, total), 1
            )
            if self._prefix is not None:
                m.update(self._prefix.stats())
        for key, hist in (("ttft", self._ttft), ("tpot", self._tpot),
                          ("migration", self._mig)):
            p50, p95, p99 = hist.percentiles_ms()
            m[f"{key}_ms_p50"] = p50
            m[f"{key}_ms_p95"] = p95
            m[f"{key}_ms_p99"] = p99
        if self.role is not None:
            # pool label: /api/serve groups each engine's token counters
            # (prefill_tokens / reused_prefix_tokens / tokens_out) and
            # migration ledger into per-pool views by this key
            m["pool"] = self.role
        try:
            g = _engine_metrics()
            g["dpt"].set(m["dispatches_per_token"], tags=self._tags)
            g["occupancy"].set(m["lane_occupancy_pct"], tags=self._tags)
        except Exception:
            pass
        return m

    def request_timeline(self, rid: str) -> List[Dict[str, Any]]:
        """One rid's process-local lifeline, time-sorted, with the
        macro-step dispatches the lane rode joined in at READ time: the
        dispatch hot path records nothing per request (one flight-ring
        write per dispatch, total), so the join scans this process's
        ring for dispatch records inside the request's [first, last]
        event window. Cluster-wide stitching (prefill→decode hop,
        redispatch attempts) happens a level up — the serve controller
        fans this out per replica and merges by rid."""
        evs = [dict(e) for e in _lifeline.events(rid)]
        ts = [e["t"] for e in evs]
        if ts:
            lo, hi = min(ts) - 1e-3, max(ts) + 1e-3
            try:
                for rec in _flightrec.read_tail(path=self._fr.path,
                                                n=self._fr.capacity):
                    if rec["kind"] == "dispatch" and lo <= rec["t"] <= hi:
                        evs.append({"t": rec["t"], "kind": "dispatch",
                                    "pid": rec["pid"],
                                    "engine": self.name,
                                    "step": rec["step"],
                                    "dispatch_ms": round(rec["a"], 3)})
            except Exception:
                pass
        evs.sort(key=lambda e: e["t"])
        return evs

    def reset_metrics(self) -> None:
        with self._m_lock:
            self._m = {k: 0 for k in self._m}
        self._ttft.reset()
        self._tpot.reset()
        self._mig.reset()
        self._tel.reset()
        if self._prefix is not None:
            for c in ("hits", "misses", "evictions", "hit_tokens",
                      "lookup_tokens"):
                setattr(self._prefix, c, 0)

    # ------------------------------------------------------------ engine
    def _bucket(self, n: int) -> int:
        """Power-of-two padded prompt width, clamped to max_len: with a
        non-power-of-two max_len (e.g. 768) the raw bucket can exceed
        the cache depth and crash prefill at trace time; submit()
        already guarantees the prompt itself fits."""
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    # ---- macro-step scheduling ----------------------------------------
    def _free_request_blocks(self, req: _Request) -> None:
        """Return a request's KV blocks to the pool (idempotent — a
        request can be planned-evicted AND repaired in either order).
        Blocks the prefix cache committed stay pinned by its reference
        until cache eviction."""
        if not self.paged or req._blocks_freed:
            return
        req._blocks_freed = True
        self._alloc.decref(req._blocks)

    def _try_admit_paged(self, req: _Request) -> bool:
        """Reserve blocks + block table for one admission. Full
        reservation (prompt + max_new, minus the reused prefix) makes
        the plan deadlock-free by construction: an admitted request can
        always take every decode step it was promised. On exhaustion the
        radix cache evicts LRU committed prefixes; False means the
        caller must leave the request queued."""
        shared: List[int] = []
        matched = 0
        if self._prefix is not None:
            # record=False: a pool-exhausted admission retries every
            # plan tick and must not inflate the hit-rate counters —
            # record_lookup() fires once, on the admission that lands
            shared, matched = self._prefix.lookup(req.prompt, record=False)
        # migrating (prefill-pool) requests reserve prompt blocks only:
        # they ship their KV after the first token, so decode-span
        # blocks would just starve the prefill pool's admission rate
        span = len(req.prompt) if req._migrate else \
            len(req.prompt) + req.max_new_tokens
        need_total = self._alloc.blocks_for_tokens(span)
        need = need_total - len(shared)
        from ray_tpu.serve._internal.kv_blocks import BlockPoolExhausted

        try:
            private = self._alloc.alloc(need)
        except BlockPoolExhausted:
            if self._prefix is not None:
                self._prefix.evict(need - self._alloc.free_blocks)
            try:
                private = self._alloc.alloc(need)
            except BlockPoolExhausted:
                if shared:
                    self._alloc.decref(shared)
                return False
        req._start = matched
        req._blocks = shared + private
        req._blocks_freed = False
        if self._prefix is not None:
            self._prefix.record_lookup(len(req.prompt), len(shared))
        with self._m_lock:
            self._m["reused_prefix_tokens"] += matched
            self._m["prefill_tokens"] += len(req.prompt) - matched
            self._m["kv_blocks_peak_in_use"] = max(
                self._m["kv_blocks_peak_in_use"], self._alloc.used_blocks
            )
        if req.rid:
            _lifeline.record(req.rid, "admit", ctx=req._trace_ctx,
                             rid_b=req._rid_b, engine=self.name,
                             matched_prefix=matched,
                             blocks=len(req._blocks),
                             a=float(matched), b=float(len(req._blocks)))
        self._dec_qtok(req)
        if self._prefix is not None:
            # commit the full prompt blocks NOW: the prefill that fills
            # them rides the same (or an earlier) phase of the very
            # dispatch this plan compiles to, and phases execute in plan
            # order — so even a same-plan admission can share them
            self._prefix.insert(req.prompt, req._blocks)
            self._register_prefix(req.prompt)
        return True

    def _table_row(self, req: Optional[_Request]) -> "np.ndarray":
        row = np.zeros(self._mb, np.int32)  # null-block padded
        if req is not None:
            row[: len(req._blocks)] = req._blocks
        return row

    def _snapshot_phase(self) -> Dict[str, Any]:
        """Per-phase device plan arrays from current slot occupancy:
        block tables + sampling params. Freed slots stay all-null, so a
        zombie lane (stopped/cancelled request still riding the plan)
        can only write the null block from this phase on."""
        from ray_tpu.serve._internal.sampling import MAX_STOP_TOKENS

        B = self.n_slots
        tables = np.zeros((B, self._mb), np.int32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        stops = np.full((B, MAX_STOP_TOKENS), -1, np.int32)
        for s, r in enumerate(self._slots):
            if r is None:
                continue
            tables[s] = self._table_row(r)
            sp = r.sampling
            temps[s] = sp.temperature
            top_ks[s] = sp.top_k
            top_ps[s] = sp.top_p
            stops[s] = sp.stop_row()
        return {"tables": tables, "temps": temps, "top_ks": top_ks,
                "top_ps": top_ps, "stops": stops}

    def _admit_resumes(self) -> None:
        """Import inbound migrations at the plan boundary: for each
        fetched payload in the resume queue, claim a free slot, reserve
        the FULL decode span, and land the KV with ONE fused scatter
        dispatch that also arms the slot (absolute position, remaining
        budget, recomputed rng). The slot then rides the next plan's
        phases as an ordinary live lane — the request continues
        mid-stream exactly where the prefill replica left it. Pool
        exhaustion leaves the head queued (FIFO, retried next tick)
        after a prefix-cache evict attempt."""
        while True:
            try:
                self._resuming.append(self._rqueue.get_nowait())
            except queue.Empty:
                break
        if not self._resuming:
            return
        import jax.numpy as jnp

        from ray_tpu.serve._internal import kv_plane
        from ray_tpu.serve._internal.kv_blocks import BlockPoolExhausted

        while self._resuming:
            req = self._resuming[0]
            if req.done.is_set():  # cancelled while queued
                self._resuming.popleft()
                continue
            slot = next(
                (i for i, r in enumerate(self._slots) if r is None), None)
            if slot is None:
                return
            need = self._alloc.blocks_for_tokens(
                len(req.prompt) + req.max_new_tokens)
            try:
                blocks = self._alloc.alloc(need)
            except BlockPoolExhausted:
                if self._prefix is not None:
                    self._prefix.evict(need - self._alloc.free_blocks)
                try:
                    blocks = self._alloc.alloc(need)
                except BlockPoolExhausted:
                    return
            self._resuming.popleft()
            payload, req._resume = req._resume, None
            req._blocks = blocks
            req._blocks_freed = False
            req._start = len(req.prompt)  # fully resident: no prefill owed
            n_data = payload["n_data"]
            dst = kv_plane.pad_block_ids(blocks[:n_data])
            if req.sampling.greedy:
                rng = np.zeros(2, np.uint32)
            else:
                # bit-exact recompute of the carried key the prefill
                # side's admission would have stored in its slot — rng
                # state never rides the wire
                rng = kv_plane.carried_rng_for_seed(req.sampling.seed or 0)
            self.cache = self._D.jitted_import_kv_blocks()(
                self.cache, jnp.asarray(dst), payload["k"], payload["v"],
                jnp.int32(slot), jnp.int32(len(req.prompt)),
                jnp.int32(req.max_new_tokens - 1), jnp.asarray(rng))
            self._next_dev = self._next_dev.at[slot].set(
                jnp.int32(payload["first"]))
            req.tokens.append(payload["first"])
            req._t_first = time.perf_counter()  # TTFT was paid at prefill
            req._remaining = req.max_new_tokens - 1
            if self.draft_params is not None:
                req._rounds_est = self._rounds_for(req._remaining) \
                    if req._remaining > 0 else 0
                req._rounds_inflight = 0
            self._slots[slot] = req
            if self._prefix is not None:
                self._prefix.insert(req.prompt, req._blocks)
                self._register_prefix(req.prompt)
            with self._m_lock:
                self._m["migrations_in"] += 1
                self._m["migrated_blocks_in"] += n_data
                self._m["kv_blocks_peak_in_use"] = max(
                    self._m["kv_blocks_peak_in_use"],
                    self._alloc.used_blocks)
            if req.rid:
                _lifeline.record(req.rid, "kv_import", ctx=req._trace_ctx,
                                 rid_b=req._rid_b, engine=self.name,
                                 blocks=n_data, a=float(n_data))
            if payload.get("t_export") is not None:
                # end-to-end handoff latency (cross-process wall clock)
                self._mig.observe(max(0.0, time.time() - payload["t_export"]))
            if req._remaining <= 0:
                # max_new_tokens == 1: the migrated first token IS the
                # whole answer (prefill normally keeps these local, but
                # a redispatched resume can land here)
                self._slots[slot] = None
                self._free_request_blocks(req)
                if _finish(req, reason="length"):
                    self._m["requests_completed"] += 1

    def _migrate_out(self, req: _Request) -> None:
        """Export a prefill-pool request's KV at its first token: ONE
        fused gather + ONE object-plane put (the migration hot path's
        entire per-handoff cost — lint-pinned), then complete the
        request with reason "migrated"; the serving layer chains the
        decode-pool call from req.export. The put synchronizes on the
        gather before returning, so the blocks free immediately after;
        the ObjectRef stays alive on req.export until the decode side's
        reply lands. Export failure is a typed RETRYABLE failure — no
        output escaped (the first token rides the resume body, not the
        caller's reply)."""
        from ray_tpu.serve._internal import kv_plane

        t0 = time.perf_counter()
        try:
            n_data = self._alloc.blocks_for_tokens(len(req.prompt))
            ref, _w = kv_plane.export_kv_blocks(
                self.cache, req._blocks[:n_data], rid=req.rid)
        except Exception as e:  # noqa: BLE001 — device/object-plane errors
            from ray_tpu.serve.errors import ReplicaDiedError

            if req.rid:
                _lifeline.record(req.rid, "error", ctx=req._trace_ctx,
                                 rid_b=req._rid_b, engine=self.name,
                                 error=f"kv export failed: "
                                       f"{type(e).__name__}")
            self._free_request_blocks(req)
            _finish(req, exc=ReplicaDiedError(
                f"kv export failed: {type(e).__name__}: {e}", started=False))
            self._wake.set()
            return
        req.export = {
            "ref": ref, "ref_hex": ref.hex(), "n_data_blocks": n_data,
            "block_size": self.block_size, "t_export": time.time(),
        }
        with self._m_lock:
            self._m["migrations_out"] += 1
            self._m["migrated_blocks_out"] += n_data
        self._mig.observe(time.perf_counter() - t0)
        req._t_done = time.perf_counter()
        if req.rid:
            _lifeline.record(req.rid, "kv_export", ctx=req._trace_ctx,
                             rid_b=req._rid_b, engine=self.name,
                             blocks=n_data, a=float(n_data),
                             b=(time.perf_counter() - t0) * 1e3)
        if _finish(req, reason="migrated"):
            dur = req._t_done - req._t_submit
            ema = self._ema_service_s
            self._ema_service_s = dur if ema <= 0.0 else 0.8 * ema + 0.2 * dur
            if req.rid:
                _lifeline.record(req.rid, "migrate", ctx=req._trace_ctx,
                                 rid_b=req._rid_b, engine=self.name,
                                 blocks=n_data)
                # terminal on THIS engine (the request lives on at the
                # decode pool, in that process's store) — age the buffer
                _lifeline.finish(req.rid)
        self._free_request_blocks(req)
        self._wake.set()

    def _plan(self) -> Optional[List[Dict[str, Any]]]:
        """Plan up to macro_phases phases of admissions + adaptive decode
        chunks purely from host counters. Greedy requests make this
        exact; sampled requests make it SPECULATIVE (a stop token can
        end them early — _deliver/_repair reconcile). Mutates engine
        bookkeeping to the post-macro-step state: slot assignments,
        per-request remaining counters, evictions, block
        allocations/frees."""
        if self.draft_params is not None:
            return self._plan_spec()
        if self.paged:
            self._admit_resumes()
        phases = []
        while len(phases) < self.macro_phases:
            admissions = []
            free = [i for i, r in enumerate(self._slots) if r is None]
            while free and self._waiting:
                req = self._waiting[0]
                if self.paged and not self._try_admit_paged(req):
                    break  # pool exhausted: stays queued, FIFO order kept
                self._waiting.popleft()
                self._dec_qtok(req)
                slot = free.pop(0)
                # migrating requests are prefill-only: zero decode steps
                # owed here, so the slot frees this very phase and the
                # device lane goes inactive right after its admission
                # prefill (rems row 0 in _dispatch_macro)
                req._remaining = 0 if req._migrate else req.max_new_tokens - 1
                self._slots[slot] = req
                admissions.append((slot, req))
            live = [(s, r) for s, r in enumerate(self._slots)
                    if r is not None and r._remaining > 0]
            if not live and not admissions:
                break
            snapshot = self._snapshot_phase() if self.paged else {}
            # adaptive chunk: decode exactly to the next scheduling event
            # (a slot finishing) so the freed lane re-admits immediately
            steps = min([self.chunk] + [r._remaining for _, r in live]) if live else 0
            # invariant: steps <= every live remaining, so each live slot
            # takes exactly `steps` real tokens this phase
            takes = []
            for s, r in live:
                r._remaining -= steps
                takes.append((s, r, steps))
            for s, r in enumerate(self._slots):
                if r is not None and r._remaining == 0:
                    self._slots[s] = None  # evict: freed for the next phase
                    if not r._migrate:
                        # a migrating request's blocks must survive to
                        # the export gather (fired from _deliver when
                        # its first token resolves) — _migrate_out and
                        # the _deliver stop/cancel paths free them
                        self._free_request_blocks(r)
            phases.append({"steps": steps, "admissions": admissions,
                           "takes": takes, **snapshot})
        return phases or None

    def _rounds_for(self, tokens_owed: int) -> int:
        """Verify rounds expected to cover `tokens_owed` tokens, from
        the acceptance EMA (clamped to [1, n_spec + 1] tokens/round)."""
        e = min(max(self._accept_ema, 1.0), float(self.n_spec + 1))
        return max(1, int(np.ceil(tokens_owed / e)))

    def _plan_spec(self) -> Optional[List[Dict[str, Any]]]:
        """Speculative plan: phases of verify ROUNDS instead of decode
        steps. Acceptance is data-dependent, so per-request round counts
        are ESTIMATES from the acceptance EMA (resynced at resolution
        against observed accepted lengths) — and, critically, slots are
        NEVER evicted at plan time: an estimate saying a request is done
        is not the request being done, and freeing its blocks while a
        live device lane still writes them would hand corrupted blocks
        to the next admission. Eviction happens only in _repair(), after
        delivery confirms completion. A lane that finishes earlier than
        estimated rides its planned rounds emitting zero-count rows (the
        device zeroed its `remaining`); a lane that finishes later gets
        more rounds planned after the resync."""
        self._admit_resumes()
        phases = []
        while len(phases) < self.macro_phases:
            admissions = []
            free = [i for i, r in enumerate(self._slots) if r is None]
            while free and self._waiting:
                req = self._waiting[0]
                if not self._try_admit_paged(req):
                    break  # pool exhausted: stays queued, FIFO order kept
                self._waiting.popleft()
                slot = free.pop(0)
                req._remaining = 0 if req._migrate else req.max_new_tokens - 1
                req._rounds_est = self._rounds_for(req._remaining) \
                    if req._remaining > 0 else 0
                req._rounds_inflight = 0
                self._slots[slot] = req
                admissions.append((slot, req))
            live = [(s, r) for s, r in enumerate(self._slots)
                    if r is not None]
            owing = [r._rounds_est for _, r in live if r._rounds_est > 0]
            if not owing and not admissions:
                break
            snapshot = self._snapshot_phase()
            steps = min([self.chunk] + owing) if owing else 0
            takes = []
            if steps > 0:
                # EVERY occupied slot rides the phase, not just the ones
                # the estimate says owe rounds: the device advances every
                # active lane each round regardless of the plan, so a
                # slot missing from `takes` would have its counts dropped
                # on the floor — lost tokens, then a device lane whose
                # `remaining` hits zero while the host still waits. Lanes
                # the estimate got right just emit zero-count rows.
                for s, r in live:
                    r._rounds_est = max(0, r._rounds_est - steps)
                    r._rounds_inflight += steps
                    takes.append((s, r, steps))
            phases.append({"steps": steps, "admissions": admissions,
                           "takes": takes, **snapshot})
        return phases or None

    def _bucket_paged(self, n: int) -> int:
        """Paged prompt bucket: power-of-two, at least one block, at
        most the table span — always a multiple of block_size (the
        suffix-prefill writes whole blocks)."""
        b = 16
        while b < n:
            b *= 2
        return min(max(b, self.block_size), self._mb * self.block_size)

    def _dispatch_macro(self, phases: List[Dict[str, Any]]) -> None:
        """Ship the plan as ONE jitted dispatch and append the result to
        the fetch frontier (resolved one macro-step behind). In paged
        mode admission rows carry only each prompt's SUFFIX beyond its
        reused prefix, and the per-phase block tables + sampling plan
        ride along as extra program arguments."""
        import jax.numpy as jnp

        K = self.macro_phases
        max_admit = max((len(p["admissions"]) for p in phases), default=0)
        A = 1
        while A < max(1, max_admit):
            A *= 2
        suffix_len = lambda r: len(r.prompt) - r._start  # noqa: E731
        if self.paged:
            P = self._bucket_paged(max(
                (suffix_len(r) for p in phases for _, r in p["admissions"]),
                default=1,
            ))
        else:
            P = self._bucket(max(
                (len(r.prompt) for p in phases for _, r in p["admissions"]),
                default=1,
            ))
        steps = np.zeros(K, np.int32)
        has_admit = np.zeros(K, bool)
        prompts = np.zeros((K, A, P), np.int32)
        lengths = np.zeros((K, A), np.int32)
        slots = np.zeros((K, A), np.int32)
        rems = np.zeros((K, A), np.int32)
        starts = np.zeros((K, A), np.int32)
        seeds = np.zeros((K, A), np.uint32)
        for k, ph in enumerate(phases):
            steps[k] = ph["steps"]
            for a, (slot, req) in enumerate(ph["admissions"]):
                has_admit[k] = True
                if self.paged:
                    suffix = req.prompt[req._start:]
                    prompts[k, a, : len(suffix)] = suffix
                    lengths[k, a] = len(suffix)
                    starts[k, a] = req._start
                    # greedy rows never consume their key; submit()
                    # materialized a real seed for every sampled row
                    seeds[k, a] = np.uint32(
                        (req.sampling.seed or 0) & 0xFFFFFFFF)
                else:
                    prompts[k, a, : len(req.prompt)] = req.prompt
                    lengths[k, a] = len(req.prompt)
                slots[k, a] = slot
                # migrating rows arm ZERO decode steps: the admission
                # prefill still samples their first token, then the lane
                # goes inactive (writes aim at the null block) — decode
                # happens on the importing replica
                rems[k, a] = 0 if req._migrate else req.max_new_tokens - 1
        t0 = time.perf_counter()
        try:
            if self.paged:
                from ray_tpu.serve._internal.sampling import MAX_STOP_TOKENS

                # static variant selection: only pay the device sampling
                # pipeline when a sampled request actually rides the plan
                plan_sampled = any(
                    not r.sampling.greedy
                    for p in phases
                    for r in ([r for _, r in p["admissions"]]
                              + [r for _, r, _ in p["takes"]])
                )
                self._macro_paged_fn = self._D.jitted_macro_step_slots_paged(
                    self.cfg, self.chunk, sampled=plan_sampled)
                B, MB = self.n_slots, self._mb
                tables = np.zeros((K, B, MB), np.int32)
                temps = np.zeros((K, B), np.float32)
                top_ks = np.zeros((K, B), np.int32)
                top_ps = np.ones((K, B), np.float32)
                stops = np.full((K, B, MAX_STOP_TOKENS), -1, np.int32)
                for k, ph in enumerate(phases):
                    tables[k] = ph["tables"]
                    temps[k] = ph["temps"]
                    top_ks[k] = ph["top_ks"]
                    top_ps[k] = ph["top_ps"]
                    stops[k] = ph["stops"]
                if self.draft_params is not None:
                    # third static variant family: the speculative macro
                    # program (drafts + batched verification per round)
                    self._macro_paged_fn = self._D.jitted_macro_step_slots_spec(
                        self.cfg, self.draft_cfg, self.chunk, self.n_spec,
                        sampled=plan_sampled)
                    (toks_dev, counts_dev, firsts_dev, self._next_dev,
                     self.cache, self.draft_cache) = self._macro_paged_fn(
                        self.params, self.draft_params, self.cache,
                        self.draft_cache, self._next_dev,
                        jnp.asarray(steps), jnp.asarray(has_admit),
                        jnp.asarray(prompts), jnp.asarray(lengths),
                        jnp.asarray(starts), jnp.asarray(slots),
                        jnp.asarray(rems), jnp.asarray(seeds),
                        jnp.asarray(tables), jnp.asarray(temps),
                        jnp.asarray(top_ks), jnp.asarray(top_ps),
                        jnp.asarray(stops),
                    )
                    self._record_dispatch(
                        t0, time.perf_counter(), self._macro_paged_fn,
                        [r for p in phases for _, r in p["admissions"]]
                        + [r for p in phases for _, r, _ in p["takes"]],
                    )
                    self._m["dispatches"] += 1
                    for ph in phases:
                        self._m["slot_steps"] += ph["steps"] * self.n_slots
                        self._m["useful_slot_steps"] += sum(
                            t for _, _, t in ph["takes"])
                    self._pending.append(
                        ("spec", (toks_dev, counts_dev), firsts_dev, phases))
                    return
                toks_dev, firsts_dev, self._next_dev, self.cache = (
                    self._macro_paged_fn(
                        self.params, self.cache, self._next_dev,
                        jnp.asarray(steps), jnp.asarray(has_admit),
                        jnp.asarray(prompts), jnp.asarray(lengths),
                        jnp.asarray(starts), jnp.asarray(slots),
                        jnp.asarray(rems), jnp.asarray(seeds),
                        jnp.asarray(tables), jnp.asarray(temps),
                        jnp.asarray(top_ks), jnp.asarray(top_ps),
                        jnp.asarray(stops),
                    )
                )
            else:
                toks_dev, firsts_dev, self._next_dev, self.cache = self._macro_fn(
                    self.params, self.cache, self._next_dev,
                    jnp.asarray(steps), jnp.asarray(has_admit), jnp.asarray(prompts),
                    jnp.asarray(lengths), jnp.asarray(slots), jnp.asarray(rems),
                )
        except Exception:
            # park the plan so _die can fail requests whose ONLY remaining
            # reference is this plan (admitted AND fully planned-out slots
            # are already evicted from the host bookkeeping)
            self._pending.append(("macro", None, None, phases))
            raise
        self._record_dispatch(
            t0, time.perf_counter(),
            self._macro_paged_fn if self.paged else self._macro_fn,
            [r for p in phases for _, r in p["admissions"]]
            + [r for p in phases for _, r, _ in p["takes"]],
        )
        self._m["dispatches"] += 1
        for ph in phases:
            self._m["slot_steps"] += ph["steps"] * self.n_slots
            self._m["useful_slot_steps"] += sum(t for _, _, t in ph["takes"])
        self._pending.append(("macro", toks_dev, firsts_dev, phases))

    def _shed_expired(self) -> None:
        """Deadline shed at plan boundaries: a QUEUED request whose
        deadline already passed gets a typed failure now instead of
        burning decode steps on a result nobody can use. In-flight
        requests run to completion (their slots are already paid for —
        evicting mid-macro-step would cost a repair for no capacity
        gain). The finished entries leave the wait queue via _repair."""
        if not self._waiting:
            return
        now = time.time()
        shed = None
        for r in self._waiting:
            d = r.sampling.deadline
            if d is not None and d <= now and not r.done.is_set():
                shed = shed or []
                shed.append((r, now - d))
        if shed:
            from ray_tpu.serve.errors import DeadlineExceededError

            for r, late in shed:
                self._m["deadline_expired"] += 1
                if r.rid:
                    _lifeline.record(r.rid, "shed", ctx=r._trace_ctx,
                                     rid_b=r._rid_b, engine=self.name,
                                     reason="DeadlineExceededError",
                                     a=late)
                    _lifeline.finish(r.rid)
                _finish(r, exc=DeadlineExceededError(
                    f"deadline passed {late:.2f}s into the queue"))

    def _repair(self) -> None:
        """Plan repair: reconcile host bookkeeping with requests that
        ended ahead of the speculative plan (device-side stop token,
        cancellation, timeout). Frees their slots and KV blocks so the
        very next _plan() can admit into them; drops finished stragglers
        from the wait queue. Runs on the engine loop thread at plan
        boundaries — the only place slot/block state is mutated."""
        for s, r in enumerate(self._slots):
            if r is not None and r.done.is_set():
                self._slots[s] = None
                self._free_request_blocks(r)
        if any(r.done.is_set() for r in self._waiting):
            for r in self._waiting:
                if r.done.is_set():
                    self._dec_qtok(r)
            self._waiting = deque(
                r for r in self._waiting if not r.done.is_set())

    def _loop_macro(self) -> None:
        while self._running:
            self._drain_queue()
            self._drain_jobs()
            self._shed_expired()
            self._repair()
            if (not self._waiting
                    and not any(r is not None for r in self._slots)
                    and self._rqueue.empty() and not self._resuming):
                while self._pending:
                    self._resolve(self._pending.popleft())
                self._repair()
                self._maybe_publish(time.perf_counter())
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            phases = self._plan()
            if phases:
                self._dispatch_macro(phases)
                # fetch one macro-step BEHIND: overlaps the one just
                # dispatched
                while len(self._pending) > 1:
                    self._resolve(self._pending.popleft())
            elif self._pending:
                # nothing plannable until in-flight results land (spec
                # mode: every resident lane's round estimate is spent) —
                # resolve the frontier NOW so the acceptance resync can
                # unblock the next plan instead of spinning
                self._resolve(self._pending.popleft())
            else:
                self._wake.wait(timeout=0.01)
                self._wake.clear()

    # ---- legacy per-chunk path (macro_phases=0): kept for A/B tests ----
    def _admit(self) -> None:
        """Move queued requests into free slots. Admissions are BATCHED:
        requests bucket by power-of-two padded prompt length and each
        bucket prefills in ONE dispatch (prefill_into_slots) — over a
        relay-attached TPU a dispatch costs ~100x its compute, so
        per-sequence prefills would dominate the whole engine."""
        import jax.numpy as jnp

        free = [i for i, r in enumerate(self._slots) if r is None]
        batch: List[tuple] = []
        while free and self._waiting:
            slot, req = free.pop(0), self._waiting.popleft()
            self._dec_qtok(req)
            # claim the slot BEFORE the prefill dispatch so a failed
            # dispatch still leaves the request reachable by _die
            self._slots[slot] = req
            batch.append((slot, req))
        if not batch:
            return
        buckets: Dict[int, List[tuple]] = {}
        for slot, req in batch:
            buckets.setdefault(self._bucket(len(req.prompt)), []).append((slot, req))
        for tb, members in buckets.items():
            prompts = np.zeros((len(members), tb), np.int32)
            lengths = np.zeros(len(members), np.int32)
            slots = np.zeros(len(members), np.int32)
            for n, (slot, req) in enumerate(members):
                prompts[n, : len(req.prompt)] = req.prompt
                lengths[n] = len(req.prompt)
                slots[n] = slot
            t0 = time.perf_counter()
            firsts, self.cache = self._prefill_slots(
                self.params, jnp.asarray(prompts), jnp.asarray(lengths),
                jnp.asarray(slots), self.cache,
            )
            self._record_dispatch(t0, time.perf_counter(), self._prefill_slots,
                                  [req for _, req in members])
            self._m["dispatches"] += 1
            rem_updates = np.zeros(len(members), np.int32)
            for n, (_slot, req) in enumerate(members):
                req._first_dev = firsts[n]
                req._remaining = req.max_new_tokens - 1
                rem_updates[n] = req._remaining
            self.cache["remaining"] = self.cache["remaining"].at[
                jnp.asarray(slots)
            ].set(jnp.asarray(rem_updates))
            live = [n for n, (_s, r) in enumerate(members) if r._remaining > 0]
            if live:
                idx = jnp.asarray(slots[live])
                self._next_dev = self._next_dev.at[idx].set(firsts[jnp.asarray(live)])

    def _loop_chunked(self) -> None:
        while self._running:
            self._drain_queue()
            self._shed_expired()
            self._repair()  # timeout/cancel: free the slot before admitting
            self._admit()
            active = [(s, r) for s, r in enumerate(self._slots) if r is not None]
            if not active:
                while self._pending:
                    self._resolve(self._pending.popleft())
                self._maybe_publish(time.perf_counter())
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            # prefill-only requests resolve without a decode chunk
            takes = []
            for slot, req in active:
                if req._remaining == 0:
                    takes.append((slot, req, 0))
                    self._slots[slot] = None
            if len(takes) == len(active):
                self._pending.append(("chunk", None, takes))
                continue
            # dispatch the next chunk fed from device-side tokens (no sync)
            t0 = time.perf_counter()
            toks_dev, self.cache = self._chunk_fn(self.params, self.cache, self._next_dev)
            self._next_dev = toks_dev[:, -1]
            self._record_dispatch(t0, time.perf_counter(), self._chunk_fn,
                                  [r for _, r in active])
            self._m["dispatches"] += 1
            self._m["slot_steps"] += self.chunk * self.n_slots
            # deterministic bookkeeping: plan takes + evictions from
            # host counters — token values never gate scheduling
            for slot, req in active:
                if req._remaining == 0:
                    continue
                take = min(req._remaining, self.chunk)
                req._remaining -= take
                self._m["useful_slot_steps"] += take
                takes.append((slot, req, take))
                if req._remaining == 0:
                    self._slots[slot] = None  # evict: freed for next admit
            self._pending.append(("chunk", toks_dev, takes))
            # fetch one chunk BEHIND: overlaps the chunk just dispatched
            while len(self._pending) > 1:
                self._resolve(self._pending.popleft())

    # ---- shared plumbing ----------------------------------------------
    def _record_dispatch(self, t0: float, t1: float, jit_fn, reqs) -> None:
        """Device-step telemetry for ONE dispatch: the host dispatch
        slice, compile-detected from the jit cache, parented under the
        trace ctx of the first traced request it serves (the rest ride
        as links). Counters only — never a device sync."""
        try:
            compiled = False
            cache_size = getattr(jit_fn, "_cache_size", None)
            if cache_size is not None:
                n = cache_size()
                key = id(jit_fn)
                seen = self._jit_cache_sizes.get(key, 0)
                compiled = n > seen
                self._jit_cache_sizes[key] = max(n, seen)
            ctxs, seen_spans = [], set()
            for r in reqs:
                c = r._trace_ctx
                if c is not None and c["span_id"] not in seen_spans:
                    seen_spans.add(c["span_id"])
                    ctxs.append(c)
            self._tel.record(
                t0, t1, compiled=compiled,
                ctx=ctxs[0] if ctxs else None,
                links=ctxs[1:] or None,
            )
            # per-dispatch flight-recorder record: ONE ring write (the
            # dispatch window in ms rides `a`) — no allocation, no
            # pickle, no RPC on this path (lint-pinned)
            self._fr.write(_EV_DISPATCH, step=self._m["dispatches"],
                           a=(t1 - t0) * 1e3)
            _engine_metrics()["dispatches"].inc(1, tags=self._tags)
            self._maybe_publish(t1)
        except Exception:
            pass

    def _maybe_publish(self, now: float) -> None:
        """Throttled /api/serve snapshot push (queued — the GCS RPC runs
        on the telemetry flusher thread, never the engine loop). Also
        called from the loop's idle branch: dispatch-time publishes
        snapshot counters BEFORE that macro's deliveries land, so
        without a final idle-time push a short burst would leave
        `requests_completed` (the SLO evaluator's good-count feed)
        permanently stale at its pre-finish value."""
        if now - self._t_snapshot < 2.0:
            return
        m = self._m
        marker = (m["dispatches"], m["requests_completed"],
                  m["shed_queue_full"] + m["shed_eta"]
                  + m["deadline_expired"])
        if marker == self._pub_marker:
            return  # idle and already published these exact counters
        self._t_snapshot = now
        self._pub_marker = marker
        try:
            from ray_tpu import observability

            observability.publish_snapshot(
                "serve", {f"engine:{self.name}": self.metrics()}
            )
        except Exception:
            pass

    def _drain_queue(self) -> None:
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                return

    def _deliver(self, req: _Request, toks) -> None:
        if req.done.is_set():
            # the speculative plan outran this request (stop token,
            # cancel, timeout): these planned steps produced tokens
            # nobody wants — the plan-and-repair bill
            self._m["wasted_steps"] += len(toks)
            if req._migrate:
                # cancelled before its first token resolved: plan-time
                # eviction skipped this request's free expecting an
                # export that now never happens
                self._free_request_blocks(req)
            return
        stopped = False
        stop_set = req.sampling.stop
        if stop_set:
            for i, t in enumerate(toks):
                if t in stop_set:
                    # truncate AT the stop: the stop token itself is not
                    # delivered; tokens speculatively decoded beyond it
                    # are waste
                    self._m["wasted_steps"] += len(toks) - i - 1
                    toks = toks[:i]
                    stopped = True
                    break
        if req._t_first is None and (req.tokens or toks or stopped):
            req._t_first = time.perf_counter()
            self._ttft.observe(req._t_first - req._t_submit)
            if req.rid:
                # once per request, not per token — the per-token path
                # below stays counters-only (lint-pinned)
                _lifeline.record(req.rid, "first_token",
                                 ctx=req._trace_ctx, rid_b=req._rid_b,
                                 engine=self.name,
                                 ttft_ms=round(
                                     (req._t_first - req._t_submit) * 1e3,
                                     3),
                                 a=(req._t_first - req._t_submit) * 1e3)
        req.tokens.extend(toks)
        self._m["tokens_out"] += len(toks)
        try:
            _engine_metrics()["tokens"].inc(len(toks), tags=self._tags)
        except Exception:
            pass
        if stopped or len(req.tokens) >= req.max_new_tokens:
            req._t_done = time.perf_counter()
            if _finish(req, reason="stop" if stopped else "length"):
                if req._t_first is not None and len(req.tokens) > 1:
                    self._tpot.observe(
                        (req._t_done - req._t_first) / (len(req.tokens) - 1)
                    )
                # SLO availability numerator: requests DELIVERED here
                # (migrated finishes count on the decode side instead)
                self._m["requests_completed"] += 1
                # service-time EMA feeding the admission ETA estimate
                dur = req._t_done - req._t_submit
                ema = self._ema_service_s
                self._ema_service_s = dur if ema <= 0.0 else 0.8 * ema + 0.2 * dur
                if req.rid:
                    _lifeline.record(req.rid, "finish",
                                     ctx=req._trace_ctx, rid_b=req._rid_b,
                                     engine=self.name,
                                     reason=req.finish_reason,
                                     tokens=len(req.tokens),
                                     a=float(len(req.tokens)), b=dur * 1e3)
                    _lifeline.finish(req.rid)
                self._wake.set()  # repair promptly: slot + blocks are free
            if req._migrate:
                # stopped AT its first token: finished here, no export —
                # reclaim the blocks plan-time eviction left pinned
                self._free_request_blocks(req)
        elif req._migrate:
            # first token resolved and the request is live: hand off to
            # the decode pool (gather + put + finish("migrated"))
            self._migrate_out(req)

    def _resolve(self, entry) -> None:
        """Fetch one macro-step's (or legacy chunk's) tokens — the only
        host sync, one dispatch behind the frontier — and deliver them
        to requests according to the plan. Dispatch is async, so a
        poisoned device program often surfaces HERE (at the blocking
        fetch), after the entry already left _pending — re-park it so
        _die can still reach its requests."""
        try:
            self._resolve_inner(entry)
        except Exception:
            self._pending.appendleft(entry)
            raise

    def _resolve_inner(self, entry) -> None:
        if entry[0] == "spec":
            _, toks_counts, firsts_dev, phases = entry
            toks_dev, counts_dev = toks_counts
            toks = np.asarray(toks_dev)      # (K, chunk, B, n_spec + 1)
            counts = np.asarray(counts_dev)  # (K, chunk, B)
            firsts = np.asarray(firsts_dev)
            for k, ph in enumerate(phases):
                for a, (_slot, req) in enumerate(ph["admissions"]):
                    self._deliver(req, [int(firsts[k, a])])
                for slot, req, take in ph["takes"]:
                    req._rounds_inflight = max(0, req._rounds_inflight - take)
                    for t in range(take):
                        c = int(counts[k, t, slot])
                        if c == 0:
                            # the device lane went inactive before this
                            # planned round — the spec-mode shape of a
                            # plan overrun
                            continue
                        self._m["spec_verify_rounds"] += 1
                        self._m["draft_proposed_tokens"] += self.n_spec
                        self._m["draft_accepted_tokens"] += c - 1
                        self._accept_ema = 0.9 * self._accept_ema + 0.1 * c
                        row = [int(x) for x in toks[k, t, slot, :c]]
                        if not req.done.is_set():
                            # a round can overshoot the request's token
                            # budget (it emits up to n_spec + 1 at once):
                            # cap delivery at what's owed and bill the
                            # excess as plan-repair waste
                            owed = req.max_new_tokens - len(req.tokens)
                            if c > owed:
                                self._m["wasted_steps"] += c - owed
                                row = row[:owed]
                        self._deliver(req, row)
                    if not req.done.is_set():
                        # resync the planner's round estimate to observed
                        # progress (the EMA moved, and the estimate this
                        # plan was built from is now stale)
                        owed = req.max_new_tokens - len(req.tokens)
                        est = self._rounds_for(owed) - req._rounds_inflight
                        if req._rounds_inflight <= 0:
                            est = max(1, est)
                        req._rounds_est = max(0, est)
            return
        if entry[0] == "macro":
            _, toks_dev, firsts_dev, phases = entry
            toks = np.asarray(toks_dev)
            firsts = np.asarray(firsts_dev)
            for k, ph in enumerate(phases):
                for a, (_slot, req) in enumerate(ph["admissions"]):
                    self._deliver(req, [int(firsts[k, a])])
                for slot, req, take in ph["takes"]:
                    if take:
                        self._deliver(req, [int(t) for t in toks[k, :take, slot]])
            return
        _, toks_dev, takes = entry
        toks = np.asarray(toks_dev) if toks_dev is not None else None
        for slot, req, take in takes:
            if req._first_dev is not None:
                self._deliver(req, [int(np.asarray(req._first_dev))])
                req._first_dev = None
            if take and toks is not None:
                self._deliver(req, [int(t) for t in toks[slot, :take]])

    def _die(self, msg: str) -> None:
        """Fail every in-flight and queued request with a diagnostic and
        mark the engine dead so submit() raises immediately — a poisoned
        device program must not surface as N generic timeouts.

        Failures are TYPED (ReplicaDiedError) with the redispatch-safety
        bit set from whether the request had already emitted tokens:
        token-less requests are safe to replay elsewhere (nothing
        escaped), partially-delivered ones must fail fast to the caller
        (a silent re-generation could diverge from output already
        observed). Every doomed request's KV blocks go back to the pool
        — engine death must leave allocator refs == radix-cache refs
        (the leak audit's invariant)."""
        from ray_tpu.serve.errors import ReplicaDiedError

        self._dead = msg
        doomed = set()
        for entry in self._pending:
            if entry[0] in ("macro", "spec"):
                for ph in entry[-1]:
                    doomed.update(r for _, r in ph["admissions"])
                    doomed.update(r for _, r, _ in ph["takes"])
            else:
                doomed.update(r for _, r, _ in entry[2])
        self._pending.clear()
        doomed.update(r for r in self._slots if r is not None)
        self._slots = [None] * self.n_slots
        doomed.update(self._waiting)
        self._waiting.clear()
        doomed.update(self._resuming)
        self._resuming.clear()
        while True:
            try:
                doomed.add(self._queue.get_nowait())
            except queue.Empty:
                break
        while True:
            try:
                doomed.add(self._rqueue.get_nowait())
            except queue.Empty:
                break
        while True:
            try:
                _fn, fut = self._jobs.get_nowait()
                fut.set_exception(RuntimeError(f"engine died: {msg}"))
            except queue.Empty:
                break
        for req in doomed:
            self._dec_qtok(req)
            self._free_request_blocks(req)
            if req.rid:
                _lifeline.record(req.rid, "error", ctx=req._trace_ctx,
                                 rid_b=req._rid_b, engine=self.name,
                                 error=f"engine died: {msg}"[:200])
                _lifeline.finish(req.rid)
            _finish(req, error=msg, exc=ReplicaDiedError(
                f"engine died: {msg}", started=len(req.tokens) > 0))

    def _loop(self) -> None:
        try:
            if self.macro_phases > 0:
                self._loop_macro()
            else:
                self._loop_chunked()
            while self._pending:  # clean shutdown: drain the frontier
                self._resolve(self._pending.popleft())
        except Exception as e:  # noqa: BLE001 — anything device-side
            msg = f"{type(e).__name__}: {e}"
            logger.exception("continuous-batching engine loop died: %s", msg)
            self._die(msg)
