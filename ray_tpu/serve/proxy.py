"""HTTP proxy actor.

Equivalent of the reference's ProxyActor (reference:
serve/_private/proxy.py:759 HTTP side): routes `route_prefix` → app
handle, JSON bodies in/out. aiohttp (uvicorn/FastAPI not in this image).
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, Optional

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class ProxyActor:
    """Runs aiohttp on a dedicated thread with its own event loop, so the
    actor is plain-sync from the runtime's perspective and never shares
    (or blocks) the CoreWorker IO loop."""

    def __init__(self, port: int = 8000):
        import concurrent.futures

        self.port = port
        self.routes: Dict[str, tuple] = {}
        self._routes_version = 0
        self._handles = {}
        # DEDICATED submit pool: handle.remote can park (zero-replica
        # window), and parked submits must neither block the event loop
        # nor exhaust the loop's shared default executor that route
        # building and stats fetches ride on
        self._submit_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="serve-proxy-submit"
        )
        self._runner = None
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True, name="serve-proxy")
        self._thread.start()
        asyncio.run_coroutine_threadsafe(self._start(), self._loop).result(timeout=30)
        # route-table freshness via controller long-poll (reference:
        # LongPollClient in the proxy; updates push instead of per-miss
        # refresh round trips)
        self._poller = threading.Thread(target=self._routes_poll_loop, daemon=True, name="proxy-longpoll")
        self._poller.start()

    def _routes_poll_loop(self):
        import logging
        import random as _rnd
        import time as _t

        from ray_tpu.serve.api import _get_controller

        log = logging.getLogger("ray_tpu.serve.proxy")
        backoff = 1.0
        last_warn = 0.0
        failures = 0
        while True:
            try:
                controller = _get_controller()
                changed = ray_tpu.get(
                    controller.listen_for_change.remote({"routes": self._routes_version}, timeout_s=20.0),
                    timeout=40.0,
                )
                if "routes" in changed:
                    self.routes = dict(changed["routes"]["data"])
                    self._routes_version = changed["routes"]["version"]
                backoff = 1.0
                failures = 0
            except Exception as e:
                # exponential backoff with jitter + a rate-limited warning:
                # a dead controller must be VISIBLE, not a silent 1s-period
                # hot-ish loop hammering the GCS forever
                failures += 1
                now = _t.monotonic()
                if now - last_warn >= 30.0:
                    last_warn = now
                    log.warning(
                        "proxy route long-poll failing (%d consecutive; "
                        "controller down?): %s — backing off %.1fs",
                        failures, e, backoff,
                    )
                _t.sleep(backoff * (0.5 + _rnd.random()))
                backoff = min(backoff * 2.0, 30.0)

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "0.0.0.0", self.port)
        await site.start()

    async def _refresh_routes(self):
        from ray_tpu.serve.api import _get_controller

        def _fetch():
            controller = _get_controller()
            return ray_tpu.get(controller.get_routes.remote())

        self.routes = await asyncio.get_running_loop().run_in_executor(None, _fetch)

    def _match_route(self, path: str):
        for prefix in sorted(self.routes, key=len, reverse=True):
            if path.startswith(prefix):
                return prefix, self.routes[prefix]
        return None, None

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        prefix, route = self._match_route(path)
        if route is None:
            await self._refresh_routes()
            prefix, route = self._match_route(path)
        if route is None:
            return web.json_response({"error": f"no route for {path}"}, status=404)
        app_name, dep_name, is_ingress = (route if len(route) == 3 else (*route, False))
        # key includes the ingress flag: a redeploy that flips it must not
        # reuse a handle with the wrong dispatch method baked in
        key = (app_name, dep_name, is_ingress)
        handle = self._handles.get(key)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            def _build():
                import os as _os

                h = DeploymentHandle(dep_name, app_name)
                if is_ingress:  # route-dispatch method baked in ONCE
                    h._method = "__serve_http_request__"
                # HTTP clients shouldn't wait the full library default on
                # a scaled-to-zero deployment, and short parks recycle
                # the submit pool's threads quickly
                h.no_replica_timeout_s = float(
                    _os.environ.get("RAY_TPU_PROXY_NO_REPLICA_TIMEOUT_S", "5.0")
                )
                h._refresh()  # blocking controller round trips — off-loop
                return h

            handle = await asyncio.get_running_loop().run_in_executor(None, _build)
            self._handles[key] = handle
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            body = {"raw": await request.text()}
        # session affinity over HTTP: an X-Serve-Session-Id header (or
        # the body's own session_id) feeds the handle's consistent-hash
        # routing so a session keeps hitting its cache-hot replica
        sid = request.headers.get("X-Serve-Session-Id")
        if sid and isinstance(body, dict):
            body.setdefault("session_id", sid)
        # deadline over HTTP: a relative seconds budget in the
        # X-Request-Deadline-S header rides into the body, where the
        # handle stamps the absolute deadline and the engine's
        # admission/shed policy enforces it
        dl = request.headers.get("X-Request-Deadline-S")
        if dl and isinstance(body, dict):
            try:
                body.setdefault("deadline_s", float(dl))
            except ValueError:
                return web.json_response(
                    {"error": f"bad X-Request-Deadline-S header: {dl!r}"},
                    status=400,
                )
        try:
            # handle.remote can BLOCK (zero-replica parking waits on the
            # membership condition; an empty-set refresh is a controller
            # round trip) — park it on the dedicated submit pool so one
            # scaled-to-zero deployment can't freeze the proxy loop or
            # starve the loop's shared default executor; parks are
            # bounded by the proxy's short no_replica_timeout_s, so pool
            # threads recycle fast and steady-state submits (µs) never
            # queue for long
            loop = asyncio.get_running_loop()
            if is_ingress:
                # path routing inside the deployment: forward (method,
                # subpath, body, query) to the replica's route dispatcher
                # (reference: proxy → mounted FastAPI app in the replica)
                sub = path[len(prefix):] or "/"
                resp = await loop.run_in_executor(
                    self._submit_pool, lambda: handle.remote(
                        request.method, sub, body, dict(request.query))
                )
            else:
                resp = await loop.run_in_executor(
                    self._submit_pool, lambda: handle.remote(body)
                )
            # native await (no executor-thread hop per request): resolves
            # on the CoreWorker loop and bridges here
            result = await resp.async_result(60)
            if isinstance(result, (dict, list, str, int, float, bool, type(None))):
                return web.json_response({"result": result})
            return web.json_response({"result": str(result)})
        except Exception as e:
            if type(e).__name__ == "_NoRouteError" or "_NoRouteError" in str(type(e)):
                return web.json_response({"error": str(e)}, status=404)
            from ray_tpu.exceptions import TaskError

            if isinstance(e, TaskError) and "_NoRouteError" in getattr(e, "traceback_str", str(e)):
                return web.json_response({"error": "no matching route"}, status=404)
            # typed failure taxonomy → HTTP: retryable failures (shed,
            # replica death) answer 503 with a Retry-After hint —
            # clients see "overloaded/recovering, come back", not a 500
            # with a stack trace; a spent deadline answers 504
            from ray_tpu.serve.errors import classify_error

            category, retryable, retry_after = classify_error(e)
            payload = {"error": str(e), "type": category,
                       "retryable": retryable}
            if category in ("shed", "replica-death"):
                headers = {"Retry-After": str(max(1, round(retry_after or 1.0)))}
                return web.json_response(payload, status=503, headers=headers)
            if category == "deadline":
                return web.json_response(payload, status=504)
            return web.json_response(payload, status=500)

    def ready(self):
        return self.port

    def routing_stats(self):
        """Per-route affinity counters from the proxy's cached handles
        (hits / spills / misses — transport_stats-style)."""
        return {
            f"{app}/{dep}": h.routing_stats()
            for (app, dep, _), h in list(self._handles.items())
        }


def start_proxy(port: int = 8000):
    """Start (or return) the HTTP proxy actor."""
    name = "SERVE_PROXY"
    try:
        return ray_tpu.get_actor(name)
    except ValueError:
        proxy = ProxyActor.options(name=name, lifetime="detached").remote(port)
        ray_tpu.get(proxy.ready.remote())
        return proxy
