"""ray_tpu.serve — model serving (reference: python/ray/serve).

A singleton controller actor reconciles declarative deployments into
replica actors (reference: serve/_private/controller.py:91); handles
route requests with power-of-two-choices over replica queue depths
(reference: _private/replica_scheduler/pow_2_scheduler.py); an aiohttp
proxy actor exposes HTTP routes (reference: _private/proxy.py —
FastAPI/uvicorn there, aiohttp here since that's what the image ships).
TPU replicas are actors with num_tpus chips running jitted inference.
"""
from ray_tpu.serve.api import (  # noqa: F401
    Application,
    batch,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    request_timeline,
    run,
    shutdown,
    status,
)
from ray_tpu.serve import loadgen  # noqa: F401
from ray_tpu.serve._internal.autoscaler import (  # noqa: F401
    AffinityConfig,
    AutoscalingConfig,
)
from ray_tpu.serve._internal.sampling import SamplingParams  # noqa: F401
from ray_tpu.serve._internal.slo import SloConfig  # noqa: F401
from ray_tpu.serve.config import build_app, deploy_config  # noqa: F401
from ray_tpu.serve.errors import (  # noqa: F401
    DeadlineExceededError,
    ReplicaDiedError,
    RequestRetryableError,
    RequestShedError,
    classify_error,
)
from ray_tpu.serve.grpc_proxy import start_grpc_proxy  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle  # noqa: F401
from ray_tpu.serve.ingress import ingress, route  # noqa: F401
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed  # noqa: F401
