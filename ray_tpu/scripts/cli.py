"""Cluster CLI: start / stop / status / submit.

Equivalent of the reference's `ray start/stop/status/job submit`
(reference: python/ray/scripts/scripts.py:566 start, :1042 stop). A
head started here is DETACHED (survives the CLI process); drivers
connect with `ray_tpu.init(address="auto")` or RAY_TPU_ADDRESS.

    python -m ray_tpu.scripts.cli start --head --num-cpus 8
    python -m ray_tpu.scripts.cli start --address tcp:HOST:PORT
    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli submit -- python my_script.py
    python -m ray_tpu.scripts.cli stop
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def cmd_start(args):
    os.environ["RAY_TPU_DETACHED"] = "1"  # children must outlive this CLI
    from ray_tpu._private import node as node_mod

    if args.head:
        session_dir = node_mod.new_session_dir()
        procs = node_mod.NodeProcesses(session_dir)
        res = node_mod.default_resources(args.num_cpus, args.num_tpus)
        procs.start_head(res, args.object_store_memory, port=args.port)
        pids = [p.pid for p in procs.procs]
        with open(os.path.join(session_dir, "cluster_pids.json"), "w") as f:
            json.dump(pids, f)
        print(f"started head: session={session_dir}")
        print(f"  GCS address: {procs.gcs_address}")
        print('  connect with: ray_tpu.init(address="auto")')
        print(f'  or from another machine: ray_tpu.init(address="{procs.gcs_address}")')
    elif args.address:
        session_dir = node_mod.new_session_dir()
        procs = node_mod.NodeProcesses(session_dir)
        res = node_mod.default_resources(args.num_cpus, args.num_tpus)
        info = procs.start_raylet(
            res, args.object_store_memory, name=f"cli{os.getpid()}", gcs_address=args.address
        )
        with open(os.path.join(session_dir, "cluster_pids.json"), "w") as f:
            json.dump([p.pid for p in procs.procs], f)
        print(f"joined cluster at {args.address} as node {info['node_id']}")
    else:
        print("start requires --head or --address", file=sys.stderr)
        sys.exit(1)


def cmd_stop(args):
    import glob

    stopped = 0
    for pids_file in glob.glob("/tmp/ray_tpu/session_*/cluster_pids.json"):
        try:
            with open(pids_file) as f:
                pids = json.load(f)
        except Exception:
            continue
        for pid in pids:
            try:
                # PIDs recycle: never kill a process that isn't ours
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    if b"ray_tpu" not in f.read():
                        continue
                os.killpg(os.getpgid(pid), signal.SIGTERM)
                stopped += 1
            except (OSError, ProcessLookupError, PermissionError):
                pass
        try:
            os.unlink(pids_file)
        except OSError:
            pass
    time.sleep(1.0)
    print(f"stopped {stopped} cluster processes")


def cmd_status(args):
    import ray_tpu

    ray_tpu.init(address="auto")
    from ray_tpu.util import state

    nodes = state.list_nodes()
    print(f"{len(nodes)} node(s):")
    for n in nodes:
        res = n["resources_total"]
        avail = n["resources_available"]
        pretty = ", ".join(f"{avail.get(k, 0):g}/{v:g} {k}" for k, v in sorted(res.items()))
        print(f"  {n['node_id'][:12]} [{n['state']}] {pretty}")
    actors = [a for a in state.list_actors() if a["state"] == "ALIVE"]
    print(f"{len(actors)} live actor(s)")
    jobs = state.list_jobs()
    print(f"{len(jobs)} job(s): " + ", ".join(f"{j['job_id'][:8]}={j['state']}" for j in jobs))
    ray_tpu.shutdown()


def cmd_submit(args):
    import shlex

    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(address=args.address or "auto")
    # preserve argv boundaries through the supervisor's `sh -c`
    entrypoint = shlex.join(args.entrypoint)
    job_id = client.submit_job(entrypoint=entrypoint)
    print(f"submitted {job_id}: {entrypoint}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout=args.timeout)
        print(f"{job_id} finished: {status}")
        print(client.get_job_logs(job_id))
        sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_debug(args):
    """Attach to a waiting remote breakpoint (reference: `ray debug`)."""
    import ray_tpu
    from ray_tpu.util import rpdb

    ray_tpu.init(address=args.address or "auto")
    bps = rpdb.list_breakpoints()
    if not bps:
        print("no active breakpoints")
        return
    for i, bp in enumerate(bps):
        print(f"[{i}] pid={bp['pid']} {bp['where']} ({bp['host']}:{bp['port']})")
    idx = args.index
    if idx is None:
        idx = 0 if len(bps) == 1 else int(input("attach to which breakpoint? "))
    bp = bps[idx]
    print(f"attaching to {bp['host']}:{bp['port']} — pdb commands apply in the remote frame")
    rpdb.connect(bp["host"], bp["port"], token=bp.get("token", ""))


def cmd_up(args):
    """Launch a cluster from a YAML config and keep the autoscaler
    reconciling until interrupted (reference: `ray up` +
    autoscaler/_private/commands.py create_or_update_cluster)."""
    os.environ["RAY_TPU_DETACHED"] = "1"  # nodes must outlive this CLI
    from ray_tpu.autoscaler.config import ClusterLauncher, load_config

    config = load_config(args.config)
    launcher = ClusterLauncher(config)
    cluster = launcher.up()
    # record pids so `ray_tpu down`/`stop` can find this cluster
    with open(os.path.join(cluster.session_dir, "cluster_pids.json"), "w") as f:
        json.dump([p.pid for p in cluster.procs.procs], f)
    print(f"cluster '{config.get('cluster_name', 'cluster')}' up: "
          f"gcs={cluster.gcs_address} session={cluster.session_dir}")
    if args.no_monitor:
        return
    print("autoscaler monitoring (ctrl-c to stop; nodes keep running)...")
    try:
        while True:
            actions = launcher.update()
            changed = False
            for group, act in actions.items():
                if act.get("launched") or act.get("terminated"):
                    changed = True
                    print(f"  [{group}] +{act.get('launched', 0)} -{act.get('terminated', 0)}")
            if changed:  # autoscaled nodes must be stoppable too
                with open(os.path.join(cluster.session_dir, "cluster_pids.json"), "w") as f:
                    json.dump([p.pid for p in cluster.procs.procs], f)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        print("monitor stopped (use `ray_tpu stop` to tear the cluster down)")


def cmd_down(args):
    """Tear down everything `up` (or start) launched on this machine."""
    cmd_stop(args)


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("config", help="path to the cluster YAML")
    p.add_argument("--no-monitor", action="store_true",
                   help="launch min_workers and exit (no autoscaling loop)")
    p.add_argument("--interval", type=float, default=5.0)
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down the local cluster")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("start", help="start a head node or join a cluster")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="GCS address of an existing cluster to join")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--object-store-memory", type=int, default=512 * 1024 * 1024)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop all local cluster processes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="show cluster nodes/actors/jobs")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("debug", help="attach to an active remote breakpoint")
    p.add_argument("--address", default=None)
    p.add_argument("--index", type=int, default=None, help="breakpoint index to attach to")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("submit", help="submit a job (everything after -- is the entrypoint)")
    p.add_argument("--address", default=None)
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    args = parser.parse_args(argv)
    if getattr(args, "entrypoint", None):
        args.entrypoint = [a for a in args.entrypoint if a != "--"]
    args.fn(args)


if __name__ == "__main__":
    main()
