"""Actor classes and handles.

Equivalent of the reference's actor machinery
(reference: python/ray/actor.py — ActorClass:544, ActorClass._remote:830,
ActorHandle:1193, ActorMethod). Actor method calls go directly
worker-to-worker over a cached connection (the reference's direct actor
transport, src/ray/core_worker/transport/direct_actor_task_submitter.cc)
with per-caller ordering.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.ids import hex_id, new_id
from ray_tpu.remote_function import _normalize_resources, _scheduling_fields


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1,
                 direct: bool = False):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._direct = direct

    def remote(self, *args, **kwargs):
        return self._handle._invoke(
            self._method_name, args, kwargs, self._num_returns, direct=self._direct
        )

    def options(self, num_returns: int = 1, direct: bool = False, **_):
        """`direct=True` opts this method into the shm-ring direct
        transport (experimental/direct_transport.py): steady-state calls
        bypass the asyncio RPC stack, falling back to RPC for ref args,
        oversized payloads, non-colocated actors and broken streams.
        Direct calls order among themselves, not against RPC calls."""
        return ActorMethod(self._handle, self._method_name, num_returns, direct=direct)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: str, class_name: str, method_meta: Dict[str, int], max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta
        self._max_task_retries = max_task_retries
        self._methods: Dict[str, ActorMethod] = {}  # per-name cache (hot path)

    @property
    def _id(self):
        return self._actor_id

    def _invoke(self, method_name, args, kwargs, num_returns, direct: bool = False):
        from ray_tpu._private.worker import get_global_core

        core = get_global_core()
        refs = core.submit_actor_task(
            self._actor_id,
            method_name,
            args,
            kwargs,
            num_returns=num_returns,
            max_task_retries=self._max_task_retries,
            direct=direct,
        )
        return refs[0] if num_returns == 1 else refs

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        m = self._methods.get(name)
        if m is None:
            m = self._methods[name] = ActorMethod(self, name, self._method_meta.get(name, 1))
        return m

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id[:12]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._class_name, self._method_meta, self._max_task_retries),
        )

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._opts = default_opts
        self._fn_id: Optional[str] = None
        self._exported_by: Optional[int] = None
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **opts) -> "ActorClass":
        merged = {**self._opts, **opts}
        ac = ActorClass(self._cls, **merged)
        ac._fn_id = self._fn_id
        ac._exported_by = self._exported_by
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu._private.worker import get_global_core, global_worker

        core = get_global_core()
        if self._fn_id is None or self._exported_by != id(core):
            self._fn_id = core.export_function(self._cls)
            self._exported_by = id(core)
        actor_id = hex_id(new_id())
        opts = self._opts
        explicit = (
            opts.get("num_cpus") is not None
            or opts.get("num_tpus") is not None
            or opts.get("num_gpus") is not None
            or bool(opts.get("resources"))
        )
        # explicit resources are held for the actor's lifetime; the default
        # 1-CPU request only gates creation (reference: actor resource
        # semantics in ray_option_utils / core worker actor creation)
        resources = _normalize_resources(opts) if explicit else {"CPU": 1.0}
        spec = {
            "task_id": hex_id(new_id()),
            "actor_id": actor_id,
            "fn_id": self._fn_id,
            "name": opts.get("name"),
            "namespace": opts.get("namespace") or getattr(global_worker, "namespace", "default"),
            "class_name": self._cls.__name__,
            "args": core.pack_args(args, kwargs),
            "returns": [],
            "resources": resources,
            "max_restarts": opts.get("max_restarts", 0),
            "max_concurrency": opts.get("max_concurrency"),
            "hold_resources": explicit,
            "lifetime": opts.get("lifetime"),
            "actor_creation": True,
            "owner_addr": core._listen_addr,
            **_scheduling_fields(opts),
        }
        core.create_actor(spec)
        method_meta = {}
        for name in dir(self._cls):
            if not name.startswith("_") and callable(getattr(self._cls, name, None)):
                method_meta[name] = 1
        return ActorHandle(actor_id, self._cls.__name__, method_meta, opts.get("max_task_retries", 0))


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """reference: python/ray/_private/worker.py:2896 get_actor."""
    from ray_tpu._private.worker import get_global_core, global_worker

    core = get_global_core()
    ns = namespace or getattr(global_worker, "namespace", "default")
    try:
        actor_id = core.gcs_request("actor.get_by_name", {"name": name, "namespace": ns})
    except Exception:
        raise ValueError(f"Failed to look up actor '{name}' in namespace '{ns}'")
    info = core.actor_info(actor_id)
    return ActorHandle(actor_id, info.get("name") or name, {})
