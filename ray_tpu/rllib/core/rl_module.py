"""RLModule — the neural policy/value container.

Equivalent of the reference's RLModule
(reference: rllib/core/rl_module/rl_module.py:237). Jax-native: params
are a pytree, forward passes are pure functions — so the same module
runs in env-runners (CPU hosts, forward_exploration) and learners (TPU,
forward_train) without framework wrappers.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class RLModule:
    """Interface: subclasses define init_params / forward."""

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Returns {"logits": ..., "vf": ...}."""
        raise NotImplementedError


class ContinuousMLPModule(RLModule):
    """MLP torso with a tanh-squashed Gaussian policy and twin Q heads —
    the SAC-family module for Box action spaces (reference analogue:
    rllib/algorithms/sac/sac_catalog default continuous nets).

    forward() returns {"mean", "log_std", "vf"}; q_value(params, obs, a)
    evaluates both critics. Actions are in [-1, 1] pre-scaling; the
    runner rescales to the env's bounds.
    """

    def __init__(self, obs_space, action_space, model_config=None):
        import numpy as np

        if not hasattr(action_space, "high"):
            raise ValueError(f"ContinuousMLPModule requires a Box action space, got {action_space}")
        model_config = model_config or {}
        self.obs_dim = int(np.prod(obs_space.shape))
        self.act_dim = int(np.prod(action_space.shape))
        self.hidden = tuple(model_config.get("hidden", (256, 256)))
        self.action_low = np.asarray(action_space.low, np.float32)
        self.action_high = np.asarray(action_space.high, np.float32)

    def _mlp_init(self, key, sizes, out_dim, out_scale=0.01):
        keys = jax.random.split(key, len(sizes))
        layers = []
        for i in range(len(sizes) - 1):
            layers.append({
                "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * (2.0 / sizes[i]) ** 0.5,
                "b": jnp.zeros((sizes[i + 1],)),
            })
        layers.append({
            "w": jax.random.normal(keys[-1], (sizes[-1], out_dim)) * out_scale,
            "b": jnp.zeros((out_dim,)),
        })
        return layers

    @staticmethod
    def _mlp_apply(layers, x):
        for layer in layers[:-1]:
            x = jnp.maximum(x @ layer["w"] + layer["b"], 0.0)
        return x @ layers[-1]["w"] + layers[-1]["b"]

    def init_params(self, rng):
        sizes = (self.obs_dim,) + self.hidden
        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        q_sizes = (self.obs_dim + self.act_dim,) + self.hidden
        return {
            "pi": self._mlp_init(k_pi, sizes, 2 * self.act_dim),
            "q1": self._mlp_init(k_q1, q_sizes, 1, out_scale=1.0),
            "q2": self._mlp_init(k_q2, q_sizes, 1, out_scale=1.0),
        }

    def forward(self, params, obs):
        out = self._mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, -10.0, 2.0)
        return {"mean": mean, "log_std": log_std, "vf": jnp.zeros(obs.shape[:-1])}

    def q_values(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        return (
            self._mlp_apply(params["q1"], x)[..., 0],
            self._mlp_apply(params["q2"], x)[..., 0],
        )

    def sample_action(self, params, obs, rng):
        """(squashed action in [-1,1], its log-prob) — the SAC
        reparameterized sample."""
        out = self.forward(params, obs)
        mean, log_std = out["mean"], out["log_std"]
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mean.shape)
        pre = mean + std * eps
        action = jnp.tanh(pre)
        # gaussian logp minus tanh jacobian (numerically-stable form)
        logp = jnp.sum(
            -0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))
            - 2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)),
            axis=-1,
        )
        return action, logp


class DiscreteConvModule(RLModule):
    """Conv torso for pixel observations — categorical policy + value
    heads (reference: rllib/core/models/configs.py:637 CNNEncoderConfig
    and the models/torch visionnet lineage).

    TPU-first: NHWC convs computed in bfloat16 with float32 accumulation
    (`preferred_element_type`) so XLA tiles them onto the MXU; params
    stay float32 masters. Strided convs downsample (no pooling ops —
    strided conv is the one XLA fuses best), layernorm on the flattened
    features keeps the head scale stable. The same forward serves PPO
    (logits = policy) and DQN (logits = Q-values).

    model_config keys:
      "filters": ((out_ch, kernel, stride), ...) — default suits 10x10
                 MinAtar-style frames; 84x84 Atari-scale frames would use
                 ((32,8,4), (64,4,2), (64,3,1)).
      "dense":   flat hidden width (default 128)
      "compute_dtype": "bfloat16" (default) | "float32"
    """

    def __init__(self, obs_space, action_space, model_config=None):
        if not hasattr(action_space, "n"):
            raise ValueError(
                f"DiscreteConvModule requires a discrete action space, got {action_space}"
            )
        if len(obs_space.shape) != 3:
            raise ValueError(
                f"DiscreteConvModule requires HxWxC observations, got {obs_space.shape}"
            )
        model_config = model_config or {}
        self.obs_shape = tuple(obs_space.shape)
        self.num_actions = int(action_space.n)
        self.filters = tuple(model_config.get("filters", ((16, 3, 1), (32, 3, 2))))
        self.dense = int(model_config.get("dense", 128))
        self.compute_dtype = jnp.dtype(model_config.get("compute_dtype", "bfloat16"))
        # trace the conv stack's flat size once, host-side
        h, w, c = self.obs_shape
        for out_ch, k, s in self.filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            c = out_ch
        if h <= 0 or w <= 0:
            raise ValueError(f"filters {self.filters} collapse {self.obs_shape} to zero")
        self.flat_dim = h * w * c

    def init_params(self, rng):
        keys = jax.random.split(rng, len(self.filters) + 3)
        convs = []
        c_in = self.obs_shape[-1]
        for i, (out_ch, k, s) in enumerate(self.filters):
            fan_in = k * k * c_in
            convs.append({
                "w": jax.random.normal(keys[i], (k, k, c_in, out_ch)) * (2.0 / fan_in) ** 0.5,
                "b": jnp.zeros((out_ch,)),
            })
            c_in = out_ch
        k_d, k_pi, k_vf = keys[-3:]
        return {
            "convs": convs,
            "ln": {"scale": jnp.ones((self.flat_dim,)), "bias": jnp.zeros((self.flat_dim,))},
            "dense": {
                "w": jax.random.normal(k_d, (self.flat_dim, self.dense)) * (2.0 / self.flat_dim) ** 0.5,
                "b": jnp.zeros((self.dense,)),
            },
            "pi": {
                "w": jax.random.normal(k_pi, (self.dense, self.num_actions)) * 0.01,
                "b": jnp.zeros((self.num_actions,)),
            },
            "vf": {
                "w": jax.random.normal(k_vf, (self.dense, 1)),
                "b": jnp.zeros((1,)),
            },
        }

    def forward(self, params, obs):
        x = obs.astype(self.compute_dtype)
        for layer, (_, _, s) in zip(params["convs"], self.filters):
            # all-bf16 conv: the TPU MXU accumulates in f32 internally;
            # an explicit f32 preferred_element_type would break the
            # autodiff transpose rule (cotangent dtype mismatch)
            x = jax.lax.conv_general_dilated(
                x,
                layer["w"].astype(self.compute_dtype),
                window_strides=(s, s),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            x = jnp.maximum(x + layer["b"].astype(self.compute_dtype), 0.0)
        x = x.astype(jnp.float32).reshape(x.shape[0], -1)
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        x = (x - mu) * jax.lax.rsqrt(var + 1e-5)
        x = x * params["ln"]["scale"] + params["ln"]["bias"]
        x = jnp.maximum(x @ params["dense"]["w"] + params["dense"]["b"], 0.0)
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        vf = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return {"logits": logits, "vf": vf}


class DiscreteMLPModule(RLModule):
    """MLP torso with categorical policy + value heads (the default
    CartPole-class module; reference analogue: catalog default MLP).

    Implements the module_class contract used by
    AlgorithmConfig.build_module: __init__(obs_space, action_space,
    model_config) — model_config keys: "hidden" (tuple of layer widths).
    """

    def __init__(self, obs_space, action_space, model_config=None):
        import numpy as np

        if not hasattr(action_space, "n"):
            raise ValueError(
                f"DiscreteMLPModule requires a discrete action space, got {action_space}"
            )
        model_config = model_config or {}
        self.obs_dim = int(np.prod(obs_space.shape))
        self.num_actions = int(action_space.n)
        self.hidden = tuple(model_config.get("hidden", (64, 64)))

    def init_params(self, rng):
        sizes = (self.obs_dim,) + tuple(self.hidden)
        keys = jax.random.split(rng, len(sizes) + 2)
        params = {"layers": []}
        for i in range(len(sizes) - 1):
            params["layers"].append(
                {
                    "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * (2.0 / sizes[i]) ** 0.5,
                    "b": jnp.zeros((sizes[i + 1],)),
                }
            )
        params["pi"] = {
            "w": jax.random.normal(keys[-2], (sizes[-1], self.num_actions)) * 0.01,
            "b": jnp.zeros((self.num_actions,)),
        }
        params["vf"] = {
            "w": jax.random.normal(keys[-1], (sizes[-1], 1)) * 1.0,
            "b": jnp.zeros((1,)),
        }
        return params

    def forward(self, params, obs):
        x = obs
        for layer in params["layers"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        vf = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return {"logits": logits, "vf": vf}
