"""RLModule — the neural policy/value container.

Equivalent of the reference's RLModule
(reference: rllib/core/rl_module/rl_module.py:237). Jax-native: params
are a pytree, forward passes are pure functions — so the same module
runs in env-runners (CPU hosts, forward_exploration) and learners (TPU,
forward_train) without framework wrappers.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class RLModule:
    """Interface: subclasses define init_params / forward."""

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Returns {"logits": ..., "vf": ...}."""
        raise NotImplementedError


class DiscreteMLPModule(RLModule):
    """MLP torso with categorical policy + value heads (the default
    CartPole-class module; reference analogue: catalog default MLP).

    Implements the module_class contract used by
    AlgorithmConfig.build_module: __init__(obs_space, action_space,
    model_config) — model_config keys: "hidden" (tuple of layer widths).
    """

    def __init__(self, obs_space, action_space, model_config=None):
        import numpy as np

        if not hasattr(action_space, "n"):
            raise ValueError(
                f"DiscreteMLPModule requires a discrete action space, got {action_space}"
            )
        model_config = model_config or {}
        self.obs_dim = int(np.prod(obs_space.shape))
        self.num_actions = int(action_space.n)
        self.hidden = tuple(model_config.get("hidden", (64, 64)))

    def init_params(self, rng):
        sizes = (self.obs_dim,) + tuple(self.hidden)
        keys = jax.random.split(rng, len(sizes) + 2)
        params = {"layers": []}
        for i in range(len(sizes) - 1):
            params["layers"].append(
                {
                    "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * (2.0 / sizes[i]) ** 0.5,
                    "b": jnp.zeros((sizes[i + 1],)),
                }
            )
        params["pi"] = {
            "w": jax.random.normal(keys[-2], (sizes[-1], self.num_actions)) * 0.01,
            "b": jnp.zeros((self.num_actions,)),
        }
        params["vf"] = {
            "w": jax.random.normal(keys[-1], (sizes[-1], 1)) * 1.0,
            "b": jnp.zeros((1,)),
        }
        return params

    def forward(self, params, obs):
        x = obs
        for layer in params["layers"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        vf = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return {"logits": logits, "vf": vf}
