"""RLModule — the neural policy/value container.

Equivalent of the reference's RLModule
(reference: rllib/core/rl_module/rl_module.py:237). Jax-native: params
are a pytree, forward passes are pure functions — so the same module
runs in env-runners (CPU hosts, forward_exploration) and learners (TPU,
forward_train) without framework wrappers.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class RLModule:
    """Interface: subclasses define init_params / forward."""

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Returns {"logits": ..., "vf": ...}."""
        raise NotImplementedError


class ContinuousMLPModule(RLModule):
    """MLP torso with a tanh-squashed Gaussian policy and twin Q heads —
    the SAC-family module for Box action spaces (reference analogue:
    rllib/algorithms/sac/sac_catalog default continuous nets).

    forward() returns {"mean", "log_std", "vf"}; q_value(params, obs, a)
    evaluates both critics. Actions are in [-1, 1] pre-scaling; the
    runner rescales to the env's bounds.
    """

    def __init__(self, obs_space, action_space, model_config=None):
        import numpy as np

        if not hasattr(action_space, "high"):
            raise ValueError(f"ContinuousMLPModule requires a Box action space, got {action_space}")
        model_config = model_config or {}
        self.obs_dim = int(np.prod(obs_space.shape))
        self.act_dim = int(np.prod(action_space.shape))
        self.hidden = tuple(model_config.get("hidden", (256, 256)))
        self.action_low = np.asarray(action_space.low, np.float32)
        self.action_high = np.asarray(action_space.high, np.float32)

    def _mlp_init(self, key, sizes, out_dim, out_scale=0.01):
        keys = jax.random.split(key, len(sizes))
        layers = []
        for i in range(len(sizes) - 1):
            layers.append({
                "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * (2.0 / sizes[i]) ** 0.5,
                "b": jnp.zeros((sizes[i + 1],)),
            })
        layers.append({
            "w": jax.random.normal(keys[-1], (sizes[-1], out_dim)) * out_scale,
            "b": jnp.zeros((out_dim,)),
        })
        return layers

    @staticmethod
    def _mlp_apply(layers, x):
        for layer in layers[:-1]:
            x = jnp.maximum(x @ layer["w"] + layer["b"], 0.0)
        return x @ layers[-1]["w"] + layers[-1]["b"]

    def init_params(self, rng):
        sizes = (self.obs_dim,) + self.hidden
        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        q_sizes = (self.obs_dim + self.act_dim,) + self.hidden
        return {
            "pi": self._mlp_init(k_pi, sizes, 2 * self.act_dim),
            "q1": self._mlp_init(k_q1, q_sizes, 1, out_scale=1.0),
            "q2": self._mlp_init(k_q2, q_sizes, 1, out_scale=1.0),
        }

    def forward(self, params, obs):
        out = self._mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, -10.0, 2.0)
        return {"mean": mean, "log_std": log_std, "vf": jnp.zeros(obs.shape[:-1])}

    def q_values(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        return (
            self._mlp_apply(params["q1"], x)[..., 0],
            self._mlp_apply(params["q2"], x)[..., 0],
        )

    def sample_action(self, params, obs, rng):
        """(squashed action in [-1,1], its log-prob) — the SAC
        reparameterized sample."""
        out = self.forward(params, obs)
        mean, log_std = out["mean"], out["log_std"]
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mean.shape)
        pre = mean + std * eps
        action = jnp.tanh(pre)
        # gaussian logp minus tanh jacobian (numerically-stable form)
        logp = jnp.sum(
            -0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))
            - 2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)),
            axis=-1,
        )
        return action, logp


class DiscreteMLPModule(RLModule):
    """MLP torso with categorical policy + value heads (the default
    CartPole-class module; reference analogue: catalog default MLP).

    Implements the module_class contract used by
    AlgorithmConfig.build_module: __init__(obs_space, action_space,
    model_config) — model_config keys: "hidden" (tuple of layer widths).
    """

    def __init__(self, obs_space, action_space, model_config=None):
        import numpy as np

        if not hasattr(action_space, "n"):
            raise ValueError(
                f"DiscreteMLPModule requires a discrete action space, got {action_space}"
            )
        model_config = model_config or {}
        self.obs_dim = int(np.prod(obs_space.shape))
        self.num_actions = int(action_space.n)
        self.hidden = tuple(model_config.get("hidden", (64, 64)))

    def init_params(self, rng):
        sizes = (self.obs_dim,) + tuple(self.hidden)
        keys = jax.random.split(rng, len(sizes) + 2)
        params = {"layers": []}
        for i in range(len(sizes) - 1):
            params["layers"].append(
                {
                    "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * (2.0 / sizes[i]) ** 0.5,
                    "b": jnp.zeros((sizes[i + 1],)),
                }
            )
        params["pi"] = {
            "w": jax.random.normal(keys[-2], (sizes[-1], self.num_actions)) * 0.01,
            "b": jnp.zeros((self.num_actions,)),
        }
        params["vf"] = {
            "w": jax.random.normal(keys[-1], (sizes[-1], 1)) * 1.0,
            "b": jnp.zeros((1,)),
        }
        return params

    def forward(self, params, obs):
        x = obs
        for layer in params["layers"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        vf = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return {"logits": logits, "vf": vf}
