"""LearnerGroup — coordinates one local or N remote Learners.

Equivalent of the reference's LearnerGroup
(reference: rllib/core/learner/learner_group.py:71, "coordinator of n
possibly-remote Learner workers"). Where the reference's multi-learner
gradient reduction is torch DDP/NCCL
(reference: core/learner/torch/torch_learner.py:384-395), here each
remote jax learner computes grads on its batch shard and the group
averages the pytrees and applies them in lockstep — params never
diverge. Intra-learner multi-device reduction is already an XLA psum
via the Learner's mesh, so "N remote learners" means N hosts, not N
chips.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class _LearnerActor:
    """Actor shell around a Learner subclass (runs in a CPU worker)."""

    def __init__(self, learner_cls, config, obs_space, action_space):
        self.learner = learner_cls(config, obs_space, action_space, mesh=config.build_learner_mesh())
        self._batch = None
        self._plan = None

    def set_batch_and_plan(self, batch, num_steps: int):
        self._batch = batch
        self._plan = self.learner.shuffled_minibatches(batch, num_steps)
        return True

    def grad_step(self, step: int):
        idx = self._plan[step]
        minibatch = {k: v[idx] for k, v in self._batch.items()}
        return self.learner.compute_grads(minibatch)

    def apply_grads(self, grads):
        self.learner.apply_grads(grads)
        return True

    def grads_on(self, batch):
        return self.learner.compute_grads(batch)

    def update(self, batch):
        return self.learner.update(batch)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
        return True

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)
        return True


class MultiAgentLearner:
    """Per-module learners updated from per-module batches (reference:
    the Learner's native MultiRLModule support — one loss/optimizer per
    module id, reference core/learner/learner.py multi-module paths).
    Local-process only; each module's params are independent trees."""

    def __init__(self, config, spaces: Dict[str, tuple]):
        self.learners = {
            mid: config.learner_class(config, o, a, mesh=None)
            for mid, (o, a) in spaces.items()
        }

    def update(self, batches: Dict[str, Dict[str, np.ndarray]]) -> Dict[str, float]:
        per_module: Dict[str, Any] = {}
        for mid, b in batches.items():
            if mid in self.learners and b:
                per_module[mid] = self.learners[mid].update(b)
        # namespaced: per-module stats under "modules", cross-module means
        # flat (a module id can then never collide with a stat key)
        out: Dict[str, Any] = {"modules": per_module}
        flat_keys = {k for s in per_module.values() for k in s}
        for k in flat_keys:
            vals = [s[k] for s in per_module.values() if k in s]
            if vals:
                out[k] = float(np.mean(vals))
        return out

    def get_weights(self):
        return {mid: l.get_weights() for mid, l in self.learners.items()}

    def set_weights(self, weights):
        for mid, w in weights.items():
            if mid in self.learners:
                self.learners[mid].set_weights(w)

    def update_once(self, batches):
        raise NotImplementedError(
            "multi-agent training currently supports the on-policy update() "
            "path only (off-policy update_once per-module is not implemented)"
        )

    def get_state(self):
        return {mid: l.get_state() for mid, l in self.learners.items()}

    def set_state(self, state):
        for mid, st in state.items():
            if mid in self.learners:
                self.learners[mid].set_state(st)


class LearnerGroup:
    def __init__(self, config, obs_space=None, action_space=None):
        self.config = config
        self.num_learners = config.num_learners
        self._local = None
        self._workers: List[Any] = []
        learner_cls = config.learner_class
        if getattr(config, "policies", None):
            if self.num_learners > 0:
                raise ValueError(
                    "multi-agent training uses the local learner "
                    "(num_learners=0); distributed multi-agent learners "
                    "are not implemented yet"
                )
            # obs_space/action_space arrive as {module_id: (obs, act)}
            self._local = MultiAgentLearner(config, obs_space)
        elif self.num_learners == 0:
            mesh = config.build_learner_mesh()
            self._local = learner_cls(config, obs_space, action_space, mesh=mesh)
        else:
            import ray_tpu

            remote_cls = ray_tpu.remote(_LearnerActor)
            self._workers = [
                remote_cls.options(num_cpus=config.num_cpus_per_learner).remote(
                    learner_cls, config, obs_space, action_space
                )
                for _ in range(self.num_learners)
            ]

    # -- update ---------------------------------------------------------------
    def _shards(self, batch: Dict[str, np.ndarray]):
        """Split `batch` row-wise across workers (remainder distributed,
        never an empty shard — empty shards would mean NaN losses averaged
        into every worker's params). Workers with no rows are skipped."""
        n = len(batch["actions"])
        splits = np.array_split(np.arange(n), len(self._workers))
        out = []
        for w, idx in zip(self._workers, splits):
            if len(idx):
                out.append((w, {k: v[idx] for k, v in batch.items()}))
        return out

    def _average_and_apply(self, results) -> Dict[str, float]:
        """Average (grads, stats) pytrees from workers, apply in lockstep."""
        import jax
        import ray_tpu

        grads = [g for g, _ in results]
        stats = [s for _, s in results]
        avg = jax.tree.map(lambda *gs: np.mean(np.stack(gs), axis=0), *grads)
        ray_tpu.get([w.apply_grads.remote(avg) for w in self._workers])
        return {k: float(np.mean([s[k] for s in stats])) for k in stats[0]} if stats else {}

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch)
        import ray_tpu

        shards = self._shards(batch)
        shard_size = min(len(s["actions"]) for _, s in shards)
        mb = min(self.config.minibatch_size, shard_size)
        num_steps = self.config.num_epochs * max(1, shard_size // mb)
        ray_tpu.get([w.set_batch_and_plan.remote(s, num_steps) for w, s in shards])
        all_stats = {}
        for step in range(num_steps):
            results = ray_tpu.get([w.grad_step.remote(step) for w, _ in shards])
            step_stats = self._average_and_apply(results)
            for k, v in step_stats.items():
                all_stats.setdefault(k, []).append(v)
        return {k: float(np.mean(v)) for k, v in all_stats.items()}

    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """ONE lockstep gradient step on `batch` (off-policy algos call this
        once per replay sample, vs update()'s epochs of minibatch SGD)."""
        if self._local is not None:
            return self._local.update_once(batch)
        import ray_tpu

        shards = self._shards(batch)
        results = ray_tpu.get([w.grads_on.remote(s) for w, s in shards])
        return self._average_and_apply(results)

    def get_td_errors(self):
        """Per-sample TD errors from the last update (PER; local learner only)."""
        if self._local is not None:
            return getattr(self._local, "td_errors", None)
        return None

    # -- weights / state --------------------------------------------------------
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._workers[0].get_weights.remote())

    def set_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
            return
        import ray_tpu

        ray_tpu.get([w.set_weights.remote(weights) for w in self._workers])

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu

        return ray_tpu.get(self._workers[0].get_state.remote())

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        import ray_tpu

        ray_tpu.get([w.set_state.remote(state) for w in self._workers])

    def stop(self) -> None:
        import ray_tpu

        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []
