"""LearnerGroup — coordinates one local or N remote Learners.

Equivalent of the reference's LearnerGroup
(reference: rllib/core/learner/learner_group.py:71, "coordinator of n
possibly-remote Learner workers"). Where the reference's multi-learner
gradient reduction is torch DDP/NCCL
(reference: core/learner/torch/torch_learner.py:384-395), here each
remote jax learner computes grads on its batch shard and the group
averages the pytrees and applies them in lockstep — params never
diverge. Intra-learner multi-device reduction is already an XLA psum
via the Learner's mesh, so "N remote learners" means N hosts, not N
chips.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class _LearnerActor:
    """Actor shell around a Learner subclass (runs in a CPU worker).
    Multi-agent configs get a MultiAgentLearner inside the same shell:
    batches/plans/grads become {module_id: ...} dicts (reference:
    learner_group.py:71 — remote learners carry MultiRLModules too)."""

    def __init__(self, learner_cls, config, obs_space, action_space):
        if getattr(config, "policies", None):
            self.learner = MultiAgentLearner(config, obs_space)
        else:
            self.learner = learner_cls(
                config, obs_space, action_space, mesh=config.build_learner_mesh()
            )
        self._batch = None
        self._plan = None

    @property
    def _multi(self) -> bool:
        return isinstance(self.learner, MultiAgentLearner)

    def set_batch_and_plan(self, batch, num_steps: int):
        self._batch = batch
        if self._multi:
            self._plan = {
                mid: self.learner.learners[mid].shuffled_minibatches(b, num_steps)
                for mid, b in batch.items()
                if mid in self.learner.learners and b
            }
        else:
            self._plan = self.learner.shuffled_minibatches(batch, num_steps)
        return True

    def grad_step(self, step: int):
        if self._multi:
            out = {}
            for mid, plan in self._plan.items():
                idx = plan[step]
                minibatch = {k: v[idx] for k, v in self._batch[mid].items()}
                out[mid] = self.learner.learners[mid].compute_grads(minibatch)
            return out
        idx = self._plan[step]
        minibatch = {k: v[idx] for k, v in self._batch.items()}
        return self.learner.compute_grads(minibatch)

    def apply_grads(self, grads):
        if self._multi:
            for mid, g in grads.items():
                self.learner.learners[mid].apply_grads(g)
            return True
        self.learner.apply_grads(grads)
        return True

    def grads_on(self, batch):
        """Returns ((grads, stats), td_errors) — td rides along so PER
        priority refresh costs zero extra actor round-trips."""
        if self._multi:
            out = {
                mid: self.learner.learners[mid].compute_grads(b)
                for mid, b in batch.items()
                if mid in self.learner.learners and b
            }
            return out, None
        result = self.learner.compute_grads(batch)
        return result, getattr(self.learner, "td_errors", None)

    def update(self, batch):
        return self.learner.update(batch)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
        return True

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)
        return True


def _namespace_stats(per_module: Dict[str, Dict[str, float]]) -> Dict[str, Any]:
    """Per-module stats under "modules", cross-module means flat (a
    module id can then never collide with a stat key)."""
    out: Dict[str, Any] = {"modules": per_module}
    for k in {k for s in per_module.values() for k in s}:
        vals = [s[k] for s in per_module.values() if k in s]
        if vals:
            out[k] = float(np.mean(vals))
    return out


class MultiAgentLearner:
    """Per-module learners updated from per-module batches (reference:
    the Learner's native MultiRLModule support — one loss/optimizer per
    module id, reference core/learner/learner.py multi-module paths).
    Local-process only; each module's params are independent trees."""

    def __init__(self, config, spaces: Dict[str, tuple]):
        self.learners = {
            mid: config.learner_class(config, o, a, mesh=None)
            for mid, (o, a) in spaces.items()
        }

    def update(self, batches: Dict[str, Dict[str, np.ndarray]]) -> Dict[str, float]:
        per_module: Dict[str, Any] = {}
        for mid, b in batches.items():
            if mid in self.learners and b:
                per_module[mid] = self.learners[mid].update(b)
        return _namespace_stats(per_module)

    def get_weights(self):
        return {mid: l.get_weights() for mid, l in self.learners.items()}

    def set_weights(self, weights):
        for mid, w in weights.items():
            if mid in self.learners:
                self.learners[mid].set_weights(w)

    def update_once(self, batches):
        raise NotImplementedError(
            "multi-agent training supports the on-policy update() path "
            "only: every off-policy caller samples FLAT replay batches, "
            "which cannot be routed to per-module learners"
        )

    def get_state(self):
        return {mid: l.get_state() for mid, l in self.learners.items()}

    def set_state(self, state):
        for mid, st in state.items():
            if mid in self.learners:
                self.learners[mid].set_state(st)


class LearnerGroup:
    def __init__(self, config, obs_space=None, action_space=None):
        self.config = config
        self.num_learners = config.num_learners
        self._local = None
        self._workers: List[Any] = []
        learner_cls = config.learner_class
        self._multi = bool(getattr(config, "policies", None))
        if self._multi and self.num_learners == 0:
            # obs_space/action_space arrive as {module_id: (obs, act)}
            self._local = MultiAgentLearner(config, obs_space)
        elif self.num_learners == 0:
            mesh = config.build_learner_mesh()
            self._local = learner_cls(config, obs_space, action_space, mesh=mesh)
        else:
            import ray_tpu

            remote_cls = ray_tpu.remote(_LearnerActor)
            self._workers = [
                remote_cls.options(num_cpus=config.num_cpus_per_learner).remote(
                    learner_cls, config, obs_space, action_space
                )
                for _ in range(self.num_learners)
            ]

    # -- update ---------------------------------------------------------------
    def _shards(self, batch):
        """Split `batch` row-wise across workers (remainder distributed,
        never an empty shard — empty shards would mean NaN losses averaged
        into every worker's params). Workers with no rows are skipped.
        Multi-agent batches ({module_id: batch}) shard each module's rows
        independently — the per-policy analogue of the dp split."""
        if self._multi:
            per_worker = [dict() for _ in self._workers]
            for mid, b in batch.items():
                n = len(b["actions"])
                for shard, idx in zip(per_worker, np.array_split(np.arange(n), len(self._workers))):
                    if len(idx):
                        shard[mid] = {k: v[idx] for k, v in b.items()}
            return [(w, s) for w, s in zip(self._workers, per_worker) if s]
        n = len(batch["actions"])
        splits = np.array_split(np.arange(n), len(self._workers))
        out = []
        for w, idx in zip(self._workers, splits):
            if len(idx):
                out.append((w, {k: v[idx] for k, v in batch.items()}))
        return out

    def _average_and_apply(self, results) -> Dict[str, float]:
        """Average (grads, stats) pytrees from workers, apply in lockstep.
        Multi-agent results are {module_id: (grads, stats)} — averaged
        per module across the workers that hold rows for it, applied on
        every worker so module params never diverge."""
        import jax
        import ray_tpu

        if self._multi:
            mids = {m for r in results for m in r}
            avg: Dict[str, Any] = {}
            per_module_stats: Dict[str, Dict[str, float]] = {}
            for mid in mids:
                gs = [r[mid][0] for r in results if mid in r]
                ss = [r[mid][1] for r in results if mid in r]
                avg[mid] = jax.tree.map(lambda *g: np.mean(np.stack(g), axis=0), *gs)
                per_module_stats[mid] = {
                    k: float(np.mean([s[k] for s in ss])) for k in ss[0]
                }
            ray_tpu.get([w.apply_grads.remote(avg) for w in self._workers])
            return _namespace_stats(per_module_stats)
        grads = [g for g, _ in results]
        stats = [s for _, s in results]
        avg = jax.tree.map(lambda *gs: np.mean(np.stack(gs), axis=0), *grads)
        ray_tpu.get([w.apply_grads.remote(avg) for w in self._workers])
        return {k: float(np.mean([s[k] for s in stats])) for k in stats[0]} if stats else {}

    def update(self, batch) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch)
        import ray_tpu

        shards = self._shards(batch)
        if self._multi:
            sizes = [len(b["actions"]) for _, s in shards for b in s.values()]
        else:
            sizes = [len(s["actions"]) for _, s in shards]
        shard_size = min(sizes)
        mb = min(self.config.minibatch_size, shard_size)
        num_steps = self.config.num_epochs * max(1, shard_size // mb)
        ray_tpu.get([w.set_batch_and_plan.remote(s, num_steps) for w, s in shards])
        all_stats = {}
        for step in range(num_steps):
            results = ray_tpu.get([w.grad_step.remote(step) for w, _ in shards])
            for k, v in self._average_and_apply(results).items():
                all_stats.setdefault(k, []).append(v)
        return {
            k: (v[-1] if k == "modules" else float(np.mean(v)))
            for k, v in all_stats.items()
        }

    def update_once(self, batch) -> Dict[str, float]:
        """ONE lockstep gradient step on `batch` (off-policy algos call this
        once per replay sample, vs update()'s epochs of minibatch SGD)."""
        if self._local is not None:
            return self._local.update_once(batch)
        if self._multi:
            raise NotImplementedError(
                "multi-agent training supports the on-policy update() path "
                "only (off-policy replay batches are flat, not per-module)"
            )
        import ray_tpu

        shards = self._shards(batch)
        replies = ray_tpu.get([w.grads_on.remote(s) for w, s in shards])
        results = [r for r, _td in replies]
        # td errors rode along with the grads; shards are contiguous row
        # splits, so concatenation restores the original batch order
        tds = [td for _r, td in replies]
        self._last_td = (
            np.concatenate([np.asarray(t) for t in tds])
            if tds and not any(t is None for t in tds)
            else None
        )
        return self._average_and_apply(results)

    def get_td_errors(self):
        """Per-sample TD errors from the last update_once, in the original
        batch row order. With remote learners they rode along with the
        grads_on replies (no extra RPC), so distributed DQN+PER refreshes
        priorities exactly like the local path
        (reference: learner_group.py:71 remote learners + PER)."""
        if self._local is not None:
            return getattr(self._local, "td_errors", None)
        return getattr(self, "_last_td", None)

    # -- weights / state --------------------------------------------------------
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._workers[0].get_weights.remote())

    def set_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
            return
        import ray_tpu

        ray_tpu.get([w.set_weights.remote(weights) for w in self._workers])

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu

        return ray_tpu.get(self._workers[0].get_state.remote())

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        import ray_tpu

        ray_tpu.get([w.set_state.remote(state) for w in self._workers])

    def stop(self) -> None:
        import ray_tpu

        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []
