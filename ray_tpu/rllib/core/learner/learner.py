"""Learner — jax-native gradient updates on an RLModule.

Equivalent of the reference's Learner
(reference: rllib/core/learner/learner.py:105). Where the reference
wraps modules in TorchDDPRLModule for multi-GPU allreduce
(reference: rllib/core/learner/torch/torch_learner.py:384-395), this
learner is a pure jitted update over a pytree: multi-device data
parallelism is a `jax.sharding.Mesh` — minibatches shard over the
'dp' axis, params are replicated, and XLA inserts the gradient psum
over ICI. No process groups, no DDP wrapper.

Algorithm-specific losses subclass and implement `compute_loss`.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class Learner:
    def __init__(self, config, obs_space=None, action_space=None, mesh=None):
        import jax
        import optax

        self.config = config
        self._jax = jax
        if obs_space is None or action_space is None:
            from ray_tpu.rllib.utils.env import env_spaces

            obs_space, action_space = env_spaces(config)
        self.module = config.build_module(obs_space, action_space)
        self.params = self.module.init_params(jax.random.PRNGKey(config.seed))

        tx = []
        if config.grad_clip is not None:
            tx.append(optax.clip_by_global_norm(config.grad_clip))
        tx.append(optax.adam(config.lr))
        self.optimizer = optax.chain(*tx)
        self.opt_state = self.optimizer.init(self.params)
        self._np_rng = np.random.default_rng(config.seed + 7)

        self.mesh = mesh
        self._batch_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(mesh, P())
            self.params = jax.device_put(self.params, replicated)
            self.opt_state = jax.device_put(self.opt_state, replicated)
            self._batch_sharding = NamedSharding(mesh, P(mesh.axis_names[0]))

        def _update_step(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(self.compute_loss, has_aux=True)(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, stats

        def _grad_step(params, batch):
            (_, stats), grads = jax.value_and_grad(self.compute_loss, has_aux=True)(params, batch)
            return grads, stats

        def _apply_step(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._update_step = jax.jit(_update_step)
        self._grad_step = jax.jit(_grad_step)
        self._apply_step = jax.jit(_apply_step)

    # -- algorithm hook ------------------------------------------------------
    def compute_loss(self, params, batch) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    # -- local update --------------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Run num_epochs of shuffled minibatch SGD over `batch`."""
        n = len(batch["actions"])
        mb = min(self.config.minibatch_size, n)
        if self.mesh is not None:
            ndev = self.mesh.devices.size
            if n < ndev:
                raise ValueError(
                    f"batch of {n} cannot shard over {ndev} learner devices; "
                    "raise train_batch_size or lower num_devices_per_learner"
                )
            # every device needs an equal, non-empty shard
            mb = max(ndev, mb - mb % ndev)
        all_stats = []
        for _ in range(self.config.num_epochs):
            perm = self._np_rng.permutation(n)
            for start in range(0, n - mb + 1, mb):
                idx = perm[start : start + mb]
                minibatch = {k: v[idx] for k, v in batch.items()}
                if self._batch_sharding is not None:
                    minibatch = self._jax.device_put(minibatch, self._batch_sharding)
                self.params, self.opt_state, stats = self._update_step(self.params, self.opt_state, minibatch)
                all_stats.append(stats)
        return {k: float(np.mean([np.asarray(s[k]) for s in all_stats])) for k in all_stats[0]} if all_stats else {}

    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """ONE gradient step on the whole `batch` (the local counterpart of
        LearnerGroup.update_once's lockstep step; off-policy learners
        override with their own single-step machinery)."""
        if self._batch_sharding is not None:
            batch = self._jax.device_put(batch, self._batch_sharding)
        self.params, self.opt_state, stats = self._update_step(self.params, self.opt_state, batch)
        return {k: float(np.asarray(v)) for k, v in stats.items()}

    # -- distributed (LearnerGroup-coordinated) update -----------------------
    def shuffled_minibatches(self, batch, num_steps: int):
        """Deterministic minibatch index plan for lockstep multi-learner SGD."""
        n = len(batch["actions"])
        mb = min(self.config.minibatch_size, n)
        out = []
        perm = self._np_rng.permutation(n)
        pos = 0
        for _ in range(num_steps):
            if pos + mb > n:
                perm = self._np_rng.permutation(n)
                pos = 0
            out.append(perm[pos : pos + mb])
            pos += mb
        return out

    def compute_grads(self, batch: Dict[str, np.ndarray]):
        grads, stats = self._grad_step(self.params, batch)
        return self._jax.tree.map(np.asarray, grads), {k: float(np.asarray(v)) for k, v in stats.items()}

    def apply_grads(self, grads) -> None:
        self.params, self.opt_state = self._apply_step(self.params, self.opt_state, grads)

    # -- weights -------------------------------------------------------------
    def get_weights(self):
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = self._jax.tree.map(np.asarray, weights)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.params = self._jax.device_put(self.params, NamedSharding(self.mesh, P()))

    def get_state(self):
        return {
            "params": self.get_weights(),
            "opt_state": self._jax.tree.map(np.asarray, self.opt_state),
        }

    def set_state(self, state) -> None:
        self.set_weights(state["params"])
        if state.get("opt_state") is not None:
            # learners with their OWN optimizers (TD3) drop this key
            self.opt_state = self._jax.tree.map(np.asarray, state["opt_state"])


