from ray_tpu.rllib.core.learner.learner import Learner  # noqa: F401
from ray_tpu.rllib.core.learner.learner_group import LearnerGroup  # noqa: F401
