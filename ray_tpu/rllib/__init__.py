"""ray_tpu.rllib — reinforcement learning (reference: rllib/).

New-stack architecture only (reference: RLModule/Learner/EnvRunner —
rllib/core/rl_module/rl_module.py:237, core/learner/learner.py:105,
env/env_runner.py:15); the torch DDP learner wrap
(core/learner/torch/torch_learner.py:384) becomes a jax learner whose
multi-learner gradient reduction is an ICI psum under pjit (or
lockstep pytree averaging across learner actors on separate hosts).
"""
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.algorithms.apex_dqn import APEXDQN, APEXDQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bc import BC, BCConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bandits import (  # noqa: F401
    LinTS,
    LinTSConfig,
    LinUCB,
    LinUCBConfig,
)
from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig  # noqa: F401
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ddpg import DDPG, DDPGConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config  # noqa: F401
from ray_tpu.rllib.algorithms.dt import DT, DTConfig  # noqa: F401
from ray_tpu.rllib.algorithms.es import ES, ESConfig  # noqa: F401
from ray_tpu.rllib.algorithms.td3 import TD3, TD3Config  # noqa: F401
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.algorithms.maddpg import MADDPG, MADDPGConfig  # noqa: F401
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.qmix import QMIX, QMIXConfig  # noqa: F401
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config  # noqa: F401
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.core.learner import Learner, LearnerGroup  # noqa: F401
from ray_tpu.rllib.core.rl_module import RLModule, DiscreteMLPModule  # noqa: F401
from ray_tpu.rllib.env import EnvRunner, SingleAgentEnvRunner  # noqa: F401
