"""learner connectors (reference: rllib/connectors/learner/ — batch
transforms applied on the learner before the update)."""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.connectors.connector import Connector


class StandardizeAdvantages(Connector):
    """Zero-mean/unit-std advantages per train batch (reference:
    learner/general_advantage_estimation.py standardization step)."""

    def __call__(self, batch, **ctx):
        if "advantages" in batch:
            adv = np.asarray(batch["advantages"], np.float32)
            batch = dict(batch)
            batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        return batch
