"""env→module connectors (reference: rllib/connectors/env_to_module/ —
observation preprocessing applied on the env runner before the module
forward)."""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.connectors.connector import Connector


class FlattenObservations(Connector):
    """Flatten any trailing obs dims to one vector per row (reference:
    env_to_module/flatten_observations.py)."""

    def __call__(self, obs, **ctx):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class NormalizeObservations(Connector):
    """Running mean/std normalization (reference:
    env_to_module/mean_std_filter.py — per-runner running filter; stats
    ride get_state so restores keep the filter)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.eps = eps
        self.clip = clip
        self._count = 0
        self._mean = None
        self._m2 = None

    def __call__(self, obs, **ctx):
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self._mean is None:
            self._mean = np.zeros(flat.shape[1], np.float64)
            self._m2 = np.zeros(flat.shape[1], np.float64)
        for row in flat:  # Welford; batches are small on env runners
            self._count += 1
            d = row - self._mean
            self._mean += d / self._count
            self._m2 += d * (row - self._mean)
        std = np.sqrt(self._m2 / max(1, self._count - 1) + self.eps)
        out = (flat - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32).reshape(obs.shape)

    def get_state(self):
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, st):
        self._count = st["count"]
        self._mean = st["mean"]
        self._m2 = st["m2"]


class NormalizePixels(Connector):
    """uint8 [0,255] HWC pixels → float32 [0,1] (reference: the
    env_to_module preprocessing rllib applies to Atari frames before the
    CNN encoder). The scale decision keys on the dtype and the
    observation SPACE's bounds — never on a batch's content, which would
    scale the same pixel intensity differently frame to frame. Float
    envs with byte-range spaces (high > 1.5) divide by `scale`; float
    envs already in [0, 1] (binary MinAtar-style frames) pass through."""

    def __init__(self, scale: float = 255.0):
        self.scale = scale

    def __call__(self, obs, *, obs_space=None, **ctx):
        obs = np.asarray(obs)
        if obs.dtype == np.uint8:
            return obs.astype(np.float32) / self.scale
        obs = obs.astype(np.float32)
        if obs_space is not None and np.max(obs_space.high) > 1.5:
            return obs / self.scale
        return obs


class FrameStack(Connector):
    """Stack the last k frames along the channel axis, per vector-env
    lane (reference: rllib frame-stacking connector over Atari: velocity
    becomes observable to a feedforward conv net).

    Stateful: keeps each lane's last k frames. The env runner passes
    `reset_lanes` (episode-boundary flags) so a new episode starts from
    a repeated first frame instead of inheriting the dead episode's
    tail. State rides get_state/set_state, so the runner's shape-probe
    snapshot/restore (single_agent_env_runner.py) keeps it clean."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames = None  # [E, H, W, C*k] rolling stack

    def __call__(self, obs, *, reset_lanes=None, **ctx):
        obs = np.asarray(obs, np.float32)
        e, c = obs.shape[0], obs.shape[-1]
        if self._frames is None or self._frames.shape[0] != e:
            self._frames = np.concatenate([obs] * self.k, axis=-1)
        else:
            self._frames = np.concatenate([self._frames[..., c:], obs], axis=-1)
            if reset_lanes is not None and np.any(reset_lanes):
                idx = np.asarray(reset_lanes, bool)
                self._frames[idx] = np.concatenate([obs[idx]] * self.k, axis=-1)
        return self._frames

    def get_state(self):
        return {"frames": None if self._frames is None else self._frames.copy()}

    def set_state(self, st):
        self._frames = st["frames"]


class OneHotDiscreteObservations(Connector):
    """Discrete obs → one-hot vectors (reference:
    env_to_module/one_hot_observations.py). Needs obs_space in ctx."""

    def __call__(self, obs, *, obs_space=None, **ctx):
        n = obs_space.n
        obs = np.asarray(obs, np.int64).reshape(-1)
        out = np.zeros((obs.shape[0], n), np.float32)
        out[np.arange(obs.shape[0]), obs] = 1.0
        return out
