"""env→module connectors (reference: rllib/connectors/env_to_module/ —
observation preprocessing applied on the env runner before the module
forward)."""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.connectors.connector import Connector


class FlattenObservations(Connector):
    """Flatten any trailing obs dims to one vector per row (reference:
    env_to_module/flatten_observations.py)."""

    def __call__(self, obs, **ctx):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class NormalizeObservations(Connector):
    """Running mean/std normalization (reference:
    env_to_module/mean_std_filter.py — per-runner running filter; stats
    ride get_state so restores keep the filter)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.eps = eps
        self.clip = clip
        self._count = 0
        self._mean = None
        self._m2 = None

    def __call__(self, obs, **ctx):
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self._mean is None:
            self._mean = np.zeros(flat.shape[1], np.float64)
            self._m2 = np.zeros(flat.shape[1], np.float64)
        for row in flat:  # Welford; batches are small on env runners
            self._count += 1
            d = row - self._mean
            self._mean += d / self._count
            self._m2 += d * (row - self._mean)
        std = np.sqrt(self._m2 / max(1, self._count - 1) + self.eps)
        out = (flat - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32).reshape(obs.shape)

    def get_state(self):
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, st):
        self._count = st["count"]
        self._mean = st["mean"]
        self._m2 = st["m2"]


class OneHotDiscreteObservations(Connector):
    """Discrete obs → one-hot vectors (reference:
    env_to_module/one_hot_observations.py). Needs obs_space in ctx."""

    def __call__(self, obs, *, obs_space=None, **ctx):
        n = obs_space.n
        obs = np.asarray(obs, np.int64).reshape(-1)
        out = np.zeros((obs.shape[0], n), np.float32)
        out[np.arange(obs.shape[0]), obs] = 1.0
        return out
