"""ConnectorV2-style data pipelines.

Equivalent of the reference's connector framework (reference:
rllib/connectors/connector_v2.py + env_to_module/, module_to_env/,
learner/ — composable transforms between the three data boundaries:
raw env output → module input, module output → env actions, and
collected episodes → learner batches). Same three pipeline slots here;
connectors are plain callables over dict batches, jax/numpy agnostic.
"""
from ray_tpu.rllib.connectors.connector import (  # noqa: F401
    Connector,
    ConnectorPipeline,
)
from ray_tpu.rllib.connectors.env_to_module import (  # noqa: F401
    FlattenObservations,
    NormalizeObservations,
    OneHotDiscreteObservations,
)
from ray_tpu.rllib.connectors.learner import (  # noqa: F401
    StandardizeAdvantages,
)
from ray_tpu.rllib.connectors.module_to_env import (  # noqa: F401
    ClipActions,
    UnsquashActions,
)
