"""module→env connectors (reference: rllib/connectors/module_to_env/ —
action postprocessing applied before env.step)."""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.connectors.connector import Connector


class ClipActions(Connector):
    """Clip continuous actions to the action-space bounds (reference:
    module_to_env/ action clipping path)."""

    def __call__(self, actions, *, action_space=None, **ctx):
        if action_space is None or not hasattr(action_space, "low"):
            return actions
        return np.clip(actions, action_space.low, action_space.high)


class UnsquashActions(Connector):
    """Map tanh-squashed [-1, 1] module outputs onto the action-space
    range (reference: unsquash_actions path in module_to_env)."""

    def __call__(self, actions, *, action_space=None, **ctx):
        if action_space is None or not hasattr(action_space, "low"):
            return actions
        low, high = action_space.low, action_space.high
        return low + (np.asarray(actions) + 1.0) * 0.5 * (high - low)
