"""Connector base + pipeline (reference: rllib/connectors/connector_v2.py
ConnectorV2 — a transform with (input, context) → output composed into
ConnectorPipelineV2; here context travels as keyword args so connectors
stay pure callables)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union


class Connector:
    """One transform in a pipeline. Subclasses override __call__.

    data is a dict batch ({"obs": ..., ...} single-agent, or
    {module_id: {...}} multi-agent at the learner boundary); ctx carries
    spaces/config when a connector needs them."""

    def __call__(self, data: Any, **ctx) -> Any:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class _FnConnector(Connector):
    """Wraps a bare callable. The pipeline's ctx surface can grow
    (obs_space, reset_lanes, ...) — a user lambda with an explicit
    keyword signature must keep working, so ctx is filtered down to the
    kwargs the callable actually declares unless it takes **kwargs."""

    def __init__(self, fn: Callable):
        self._fn = fn
        try:
            import inspect

            params = inspect.signature(fn).parameters.values()
            self._pass_all = any(p.kind == p.VAR_KEYWORD for p in params)
            self._accepts = frozenset(
                p.name for p in params
                if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
            )
        except (TypeError, ValueError):  # builtins without signatures
            self._pass_all = True
            self._accepts = frozenset()

    def __call__(self, data: Any, **ctx) -> Any:
        if not self._pass_all:
            ctx = {k: v for k, v in ctx.items() if k in self._accepts}
        return self._fn(data, **ctx)

    def __repr__(self):
        return getattr(self._fn, "__name__", "fn")


class ConnectorPipeline(Connector):
    """Ordered connector composition (reference: ConnectorPipelineV2;
    append/prepend match its mutation API so algorithms can inject
    defaults around user connectors)."""

    def __init__(self, connectors: Optional[Sequence[Union[Connector, Callable]]] = None):
        self.connectors: List[Connector] = [self._wrap(c) for c in (connectors or [])]

    @staticmethod
    def _wrap(c) -> Connector:
        return c if isinstance(c, Connector) else _FnConnector(c)

    def append(self, connector) -> "ConnectorPipeline":
        self.connectors.append(self._wrap(connector))
        return self

    def prepend(self, connector) -> "ConnectorPipeline":
        self.connectors.insert(0, self._wrap(connector))
        return self

    def __call__(self, data: Any, **ctx) -> Any:
        for c in self.connectors:
            data = c(data, **ctx)
        return data

    def __repr__(self):
        return f"ConnectorPipeline({', '.join(map(repr, self.connectors))})"
