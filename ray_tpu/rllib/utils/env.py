"""Env construction helpers shared by runners, learners and the algorithm.

One place for the callable-vs-registry branch so env instantiation can't
drift between the spaces probe and the actual sampling envs
(reference: rllib env creation via gym.make / EnvContext in
rllib/env/utils.py).
"""
from __future__ import annotations


def make_single_env(config):
    import gymnasium as gym

    if callable(config.env):
        return config.env(config.env_config)
    from ray_tpu.rllib.env import ensure_registered

    ensure_registered(config.env)
    return gym.make(config.env, **(config.env_config or {}))


def make_vector_env(config):
    import gymnasium as gym

    from ray_tpu.rllib.env import ensure_registered

    ensure_registered(config.env)
    if callable(config.env):
        return gym.vector.SyncVectorEnv(
            [lambda: config.env(config.env_config) for _ in range(config.num_envs_per_env_runner)]
        )
    return gym.make_vec(
        config.env,
        num_envs=config.num_envs_per_env_runner,
        vectorization_mode="sync",
        **(config.env_config or {}),
    )


def make_same_step_vector_env(config):
    """Vector env in SAME_STEP autoreset mode, for collectors feeding
    lane-strided sequence replay (R2D2, DreamerV3): the reset obs
    arrives in the step() that reports done, so `first = done` marks the
    true episode start and no fabricated NEXT_STEP autoreset frame
    (dead episode's final obs + ignored action + reward 0) enters the
    ring — per-lane row skipping would break lane alignment, so the
    NEXT_STEP masking used by OffPolicyEnvRunner is not an option there.
    Forces sync vectorization: native vector entry points (e.g.
    CartPole-v1's) reject vector_kwargs.
    """
    import gymnasium as gym
    from gymnasium.vector import AutoresetMode

    n = config.num_envs_per_env_runner
    if callable(config.env):
        return gym.vector.SyncVectorEnv(
            [lambda: config.env(config.env_config) for _ in range(n)],
            autoreset_mode=AutoresetMode.SAME_STEP,
        )
    return gym.make_vec(
        config.env,
        num_envs=n,
        vectorization_mode="sync",
        vector_kwargs={"autoreset_mode": AutoresetMode.SAME_STEP},
        **(config.env_config or {}),
    )


def module_obs_space_for(config, obs_space):
    """The observation space the MODULE sees: the env space pushed
    through the env_to_module connector pipeline (shape probe only).
    Stateful connector state is snapshotted and restored around the
    probe — build_connector wraps the instances held ON the config, so
    without the restore a running normalizer would fold the synthetic
    zero frame into statistics every runner later inherits. Mirrors the
    probe in single_agent_env_runner.py; learners must build modules
    against this, not the raw env space."""
    build_conn = getattr(config, "build_connector", None)
    if build_conn is None:
        return obs_space
    conn = build_conn("env_to_module")
    if conn is None:
        return obs_space
    import gymnasium as gym
    import numpy as np

    saved = [(c, c.get_state()) for c in conn.connectors if hasattr(c, "get_state")]
    try:
        probe = np.asarray(
            conn(np.zeros((1,) + obs_space.shape, np.float32), obs_space=obs_space),
            np.float32,
        )
    finally:
        for c, st in saved:
            c.set_state(st)
    if probe.shape[1:] == obs_space.shape:
        return obs_space
    return gym.spaces.Box(-np.inf, np.inf, probe.shape[1:], np.float32)


def env_spaces(config):
    """(observation_space, action_space) from one throwaway env."""
    env = make_single_env(config)
    spaces = (env.observation_space, env.action_space)
    env.close()
    return spaces
