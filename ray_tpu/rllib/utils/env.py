"""Env construction helpers shared by runners, learners and the algorithm.

One place for the callable-vs-registry branch so env instantiation can't
drift between the spaces probe and the actual sampling envs
(reference: rllib env creation via gym.make / EnvContext in
rllib/env/utils.py).
"""
from __future__ import annotations


def make_single_env(config):
    import gymnasium as gym

    if callable(config.env):
        return config.env(config.env_config)
    return gym.make(config.env, **(config.env_config or {}))


def make_vector_env(config):
    import gymnasium as gym

    if callable(config.env):
        return gym.vector.SyncVectorEnv(
            [lambda: config.env(config.env_config) for _ in range(config.num_envs_per_env_runner)]
        )
    return gym.make_vec(
        config.env,
        num_envs=config.num_envs_per_env_runner,
        vectorization_mode="sync",
        **(config.env_config or {}),
    )


def env_spaces(config):
    """(observation_space, action_space) from one throwaway env."""
    env = make_single_env(config)
    spaces = (env.observation_space, env.action_space)
    env.close()
    return spaces
