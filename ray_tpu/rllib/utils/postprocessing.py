"""Trajectory postprocessing: GAE advantages / value targets.

Equivalent of the reference's GAE learner connector
(reference: rllib/connectors/learner/general_advantage_estimation.py and
rllib/evaluation/postprocessing.py compute_advantages). Pure numpy —
runs on the env-runner host right after sampling, so the learner batch
arrives flat and device-ready.
"""
from __future__ import annotations

import numpy as np


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    next_values: np.ndarray,
    terminateds: np.ndarray,
    dones: np.ndarray,
    gamma: float = 0.99,
    lambda_: float = 0.95,
):
    """Generalized Advantage Estimation over [num_envs, T] arrays.

    The caller supplies `next_values[e, t] = V(s_{t+1})` with truncation
    handled: at a truncated step it must be V(final_observation) (the
    state the time limit cut, not the auto-reset state); at a terminated
    step its value is irrelevant (masked to 0 by `terminateds`). `dones`
    = terminated | truncated resets the lambda-trace so no credit leaks
    across episode boundaries.

    Returns (advantages, value_targets), both [num_envs, T] float32.
    """
    rewards = rewards.astype(np.float32)
    values = values.astype(np.float32)
    num_envs, horizon = rewards.shape
    advantages = np.zeros((num_envs, horizon), dtype=np.float32)
    not_done = 1.0 - dones.astype(np.float32)
    not_terminated = 1.0 - terminateds.astype(np.float32)
    last_gae = np.zeros((num_envs,), dtype=np.float32)
    for t in range(horizon - 1, -1, -1):
        delta = rewards[:, t] + gamma * next_values[:, t] * not_terminated[:, t] - values[:, t]
        last_gae = delta + gamma * lambda_ * not_done[:, t] * last_gae
        advantages[:, t] = last_gae
    value_targets = advantages + values
    return advantages, value_targets
