"""Replay buffers for off-policy algorithms.

Equivalent of the reference's replay buffer utilities
(reference: rllib/utils/replay_buffers/replay_buffer.py and
prioritized_replay_buffer.py). Storage is preallocated numpy ring
buffers keyed by field — batches come out as flat dicts of contiguous
arrays, ready for a single device_put into the jitted learner step.
The prioritized variant uses a segment (sum) tree for O(log n)
proportional sampling, like the reference's sum-segment-tree
(reference: rllib/utils/replay_buffers/utils.py segment trees).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer over transition dicts."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._storage: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def _ensure_storage(self, batch: Dict[str, np.ndarray]) -> None:
        if self._storage:
            return
        for k, v in batch.items():
            v = np.asarray(v)
            self._storage[k] = np.empty((self.capacity,) + v.shape[1:], dtype=v.dtype)

    def add(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """Append a batch of transitions (each value shaped (N, ...));
        returns the storage indices written."""
        self._ensure_storage(batch)
        n = len(next(iter(batch.values())))
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idx] = np.asarray(v)
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._on_add(idx)
        return idx

    def _on_add(self, idx: np.ndarray) -> None:  # PER hook
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}


class _SumTree:
    """Flat-array binary sum tree; leaves padded to a power of two so
    every root-to-leaf path has equal depth."""

    def __init__(self, capacity: int):
        self.capacity = 1 << (max(1, capacity) - 1).bit_length()
        self.tree = np.zeros(2 * self.capacity, dtype=np.float64)

    def set(self, idx: np.ndarray, value: np.ndarray) -> None:
        i = np.asarray(idx) + self.capacity
        self.tree[i] = value
        i //= 2
        # propagate sums up; vectorized per level (dedupe parents)
        while i[0] >= 1 if len(i) else False:
            i = np.unique(i)
            self.tree[i] = self.tree[2 * i] + self.tree[2 * i + 1]
            if i[0] == 1:
                break
            i //= 2

    def total(self) -> float:
        return float(self.tree[1])

    def find(self, prefix_sums: np.ndarray) -> np.ndarray:
        """Leaf indices whose cumulative-sum interval contains each prefix."""
        idx = np.ones(len(prefix_sums), dtype=np.int64)
        s = prefix_sums.astype(np.float64).copy()
        while idx[0] < self.capacity:
            left = 2 * idx
            go_right = s > self.tree[left]
            s -= np.where(go_right, self.tree[left], 0.0)
            idx = np.where(go_right, left + 1, left)
        return idx - self.capacity


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized experience replay (Schaul et al. 2015;
    reference: rllib/utils/replay_buffers/prioritized_replay_buffer.py)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0, eps: float = 1e-6):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._tree = _SumTree(int(capacity))
        self._max_priority = 1.0
        self._last_idx: Optional[np.ndarray] = None

    def _on_add(self, idx: np.ndarray) -> None:
        self._tree.set(idx, np.full(len(idx), self._max_priority ** self.alpha))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self._tree.total()
        prefixes = self._rng.random(batch_size) * total
        idx = np.clip(self._tree.find(prefixes), 0, self._size - 1)
        self._last_idx = idx
        probs = self._tree.tree[idx + self._tree.capacity] / max(total, 1e-12)
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-self.beta)
        weights /= weights.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["weights"] = weights.astype(np.float32)
        return out

    def add_with_priorities(self, batch: Dict[str, np.ndarray],
                            priorities: Optional[np.ndarray] = None) -> None:
        """Append with producer-computed initial priorities (APEX: the
        env runner scores its own transitions by TD error so fresh data
        competes immediately instead of entering at max priority)."""
        idx = self.add(batch)
        if priorities is not None:
            prios = (np.abs(np.asarray(priorities, np.float64)) + self.eps) ** self.alpha
            self._tree.set(idx, prios)
            if len(priorities):
                self._max_priority = max(
                    self._max_priority, float(np.abs(priorities).max() + self.eps)
                )

    def update_priorities(self, td_errors: np.ndarray) -> None:
        """Re-prioritize the transitions returned by the last sample()."""
        if self._last_idx is None:
            return
        prios = (np.abs(np.asarray(td_errors, np.float64)) + self.eps) ** self.alpha
        self._tree.set(self._last_idx, prios)
        self._max_priority = max(self._max_priority, float(np.abs(td_errors).max() + self.eps))
        self._last_idx = None
