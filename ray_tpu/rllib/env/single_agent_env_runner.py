"""SingleAgentEnvRunner — vectorized gymnasium sampling.

Equivalent of the reference's SingleAgentEnvRunner
(reference: rllib/env/single_agent_env_runner.py), jax-native: the
policy forward is the RLModule's pure function jitted on the host CPU
(worker processes never grab the TPU — raylet sets JAX_PLATFORMS=cpu),
actions are sampled with a jax PRNG, and GAE runs here in numpy so the
learner receives a flat, device-ready batch.

Gymnasium >=1.0 vector envs autoreset in NEXT_STEP mode: the step after
a terminated/truncated step ignores the action and returns the reset
observation with reward 0. Those reset frames are masked out of the
batch (valid = ~prev_done), and the observation returned *at* the done
step is the true terminal state, so V(next_obs) is correct for
truncation bootstraps with no special casing.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.env.env_runner import EnvRunner
from ray_tpu.rllib.utils.postprocessing import compute_gae


class SingleAgentEnvRunner(EnvRunner):
    def __init__(self, config, worker_index: int = 0):
        import jax

        self.config = config
        self.worker_index = worker_index
        self._jax = jax
        self.env = self._make_env(config)
        self.num_envs = config.num_envs_per_env_runner
        # connector pipelines come FIRST: a shape-changing env→module
        # connector (e.g. one-hot) means the module must be built against
        # the TRANSFORMED observation space
        build_conn = getattr(config, "build_connector", None)
        self._env_conn = build_conn("env_to_module") if build_conn else None
        self._act_conn = build_conn("module_to_env") if build_conn else None
        # shape probe with state snapshot/restore — one implementation,
        # shared with EnvRunnerGroup.spaces() so runner and learner can
        # never disagree about the module's obs space
        from ray_tpu.rllib.utils.env import module_obs_space_for

        module_obs_space = module_obs_space_for(config, self.env.single_observation_space)
        # what the MODULE consumes — EnvRunnerGroup.spaces() must hand
        # this (not the raw env space) to the learner, or a
        # shape-changing connector (FrameStack, one-hot) desyncs the
        # learner's module from the sampled batches
        self.module_obs_space = module_obs_space
        self.module = config.build_module(module_obs_space, self.env.single_action_space)
        self._rng = jax.random.PRNGKey(config.seed + 1000 * (worker_index + 1))
        self.params = self.module.init_params(self._rng)
        self._weights_seq = 0

        import jax.numpy as jnp

        def _forward_sample(params, obs, rng):
            out = self.module.forward(params, obs)
            logits = out["logits"]
            action = jax.random.categorical(rng, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, action[:, None], axis=1)[:, 0]
            return action, logp, out["vf"]

        self._forward = jax.jit(_forward_sample)
        self._value_fn = jax.jit(lambda params, obs: self.module.forward(params, obs)["vf"])

        seed = config.seed + 10_000 * (worker_index + 1)
        self._obs, _ = self.env.reset(seed=seed)
        # module-view observations: what the module consumes AND what the
        # train batch stores (transform may change the obs shape, e.g.
        # one-hot). Transform each obs exactly ONCE (stateful connectors
        # like running normalizers must not see the same frame twice).
        self._mod_obs = self._transform_obs(self._obs)
        self._prev_done = np.zeros((self.num_envs,), dtype=bool)
        # Running per-env episode accounting (survives fragment edges).
        self._init_episode_accounting(self.num_envs)

    def _transform_obs(self, obs, reset_lanes=None):
        obs = np.asarray(obs, np.float32)
        if self._env_conn is None:
            return obs
        return np.asarray(
            self._env_conn(
                obs,
                obs_space=self.env.single_observation_space,
                reset_lanes=reset_lanes,
            ),
            np.float32,
        )

    @staticmethod
    def _make_env(config):
        from ray_tpu.rllib.utils.env import make_vector_env

        return make_vector_env(config)

    # -- weights -----------------------------------------------------------
    def get_weights(self):
        return self.params

    def set_weights(self, weights, seq: Optional[int] = None) -> None:
        self.params = self._jax.tree.map(np.asarray, weights)
        if seq is not None:
            self._weights_seq = seq

    # -- sampling ----------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        T = self.config.rollout_fragment_length
        E = self.num_envs
        obs_shape = self._mod_obs.shape[1:]
        obs_buf = np.empty((E, T) + obs_shape, dtype=np.float32)
        act_buf = np.empty((E, T), dtype=np.int64)
        logp_buf = np.empty((E, T), dtype=np.float32)
        vf_buf = np.empty((E, T), dtype=np.float32)
        rew_buf = np.empty((E, T), dtype=np.float32)
        term_buf = np.zeros((E, T), dtype=bool)
        done_buf = np.zeros((E, T), dtype=bool)
        valid_buf = np.zeros((E, T), dtype=bool)
        next_obs_buf = np.empty((E, T) + obs_shape, dtype=np.float32)

        obs = self._obs
        mod_obs = self._mod_obs
        prev_done = self._prev_done
        for t in range(T):
            self._rng, key = self._jax.random.split(self._rng)
            action, logp, vf = self._forward(self.params, mod_obs, key)
            action = np.asarray(action)
            env_action = action
            if self._act_conn is not None:
                env_action = self._act_conn(action, action_space=self.env.single_action_space)
            obs_buf[:, t] = mod_obs
            act_buf[:, t] = action
            logp_buf[:, t] = np.asarray(logp)
            vf_buf[:, t] = np.asarray(vf)
            valid_buf[:, t] = ~prev_done

            next_obs, reward, terminated, truncated, _ = self.env.step(env_action)
            done = terminated | truncated
            # lanes where the PREVIOUS step ended just delivered their
            # reset observation (NEXT_STEP autoreset) — stateful
            # connectors (FrameStack) start those lanes fresh
            mod_next = self._transform_obs(next_obs, reset_lanes=prev_done)
            rew_buf[:, t] = reward
            term_buf[:, t] = terminated
            done_buf[:, t] = done
            next_obs_buf[:, t] = mod_next

            self._account_step(reward, done, prev_done)

            obs = next_obs
            mod_obs = mod_next
            prev_done = done
        self._obs = obs
        self._mod_obs = mod_obs
        self._prev_done = prev_done

        if getattr(self.config, "batch_mode", "complete") == "time_major":
            # sequence batches for v-trace learners (IMPALA/APPO): no GAE —
            # the learner computes values under ITS OWN params and applies
            # the off-policy correction (reference: rllib vtrace over
            # time-major SampleBatches, algorithms/impala/)
            metrics = self._drain_episode_metrics(valid_buf.sum(), self._weights_seq)
            return {
                "batch": {
                    "obs": obs_buf,
                    "actions": act_buf,
                    "behavior_logp": logp_buf,
                    "rewards": rew_buf,
                    "terminateds": term_buf,
                    "dones": done_buf,
                    "valid": valid_buf,
                    "next_obs": next_obs_buf,
                },
                "metrics": metrics,
            }

        # next_values[e,t] = V(obs returned at t) — the true next state,
        # terminal states included (masked by `terminateds` inside GAE).
        flat_next = next_obs_buf.reshape((E * T,) + obs_shape).astype(np.float32)
        next_values = np.asarray(self._value_fn(self.params, flat_next)).reshape(E, T)
        advantages, value_targets = compute_gae(
            rew_buf,
            vf_buf,
            next_values,
            term_buf,
            done_buf,
            gamma=self.config.gamma,
            lambda_=self.config.lambda_,
        )

        mask = valid_buf.reshape(-1)
        batch = {
            "obs": obs_buf.reshape((E * T,) + obs_shape)[mask],
            "actions": act_buf.reshape(-1)[mask],
            "logp_old": logp_buf.reshape(-1)[mask],
            "values": vf_buf.reshape(-1)[mask],
            "advantages": advantages.reshape(-1)[mask],
            "value_targets": value_targets.reshape(-1)[mask],
        }
        # report-and-clear: each completed episode is reported exactly once;
        # smoothing over a trailing window happens in the Algorithm.
        metrics = self._drain_episode_metrics(mask.sum(), self._weights_seq)
        return {"batch": batch, "metrics": metrics}

    def stop(self) -> None:
        self.env.close()
