"""EnvRunner — the sampling-side worker interface.

Equivalent of the reference's EnvRunner ABC
(reference: rllib/env/env_runner.py:15). Instances run either inline in
the driver (num_env_runners=0) or as ray_tpu actors on CPU hosts; the
learner never steps an environment.
"""
from __future__ import annotations

from typing import Any, Dict


class EnvRunner:
    def sample(self) -> Dict[str, Any]:
        """Collect one rollout fragment; returns a flat train batch plus
        sampling metrics under the "metrics" key."""
        raise NotImplementedError

    def get_weights(self) -> Any:
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        pass
