"""EnvRunner — the sampling-side worker interface.

Equivalent of the reference's EnvRunner ABC
(reference: rllib/env/env_runner.py:15). Instances run either inline in
the driver (num_env_runners=0) or as ray_tpu actors on CPU hosts; the
learner never steps an environment.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np


class EnvRunner:
    def sample(self) -> Dict[str, Any]:
        """Collect one rollout fragment; returns a flat train batch plus
        sampling metrics under the "metrics" key."""
        raise NotImplementedError

    # -- shared per-env episode accounting --------------------------------
    # The gymnasium>=1.0 autoreset ordering invariants live HERE, once:
    # rewards only accrue to live envs (the frame after a done carries a
    # stale action), an episode completes on `done & live`, and envs that
    # were reset this step (prev_done) start their accounting fresh.

    def _init_episode_accounting(self, num_envs: int) -> None:
        self._ep_return = np.zeros((num_envs,), dtype=np.float64)
        self._ep_len = np.zeros((num_envs,), dtype=np.int64)
        self._completed_returns: list = []
        self._completed_lengths: list = []

    def _account_step(self, reward, done, prev_done) -> np.ndarray:
        """Fold one vector-env step into the running accounts; returns the
        `live` mask (frames that carry a real transition)."""
        live = ~prev_done
        self._ep_return[live] += reward[live]
        self._ep_len[live] += 1
        for e in np.nonzero(done & live)[0]:
            self._completed_returns.append(float(self._ep_return[e]))
            self._completed_lengths.append(int(self._ep_len[e]))
            self._ep_return[e] = 0.0
            self._ep_len[e] = 0
        self._ep_return[prev_done] = 0.0
        self._ep_len[prev_done] = 0
        return live

    def _drain_episode_metrics(self, num_env_steps: int, weights_seq: int) -> Dict[str, Any]:
        metrics = {
            "num_env_steps": int(num_env_steps),
            "episode_returns": self._completed_returns,
            "episode_lengths": self._completed_lengths,
            "weights_seq": weights_seq,
        }
        self._completed_returns = []
        self._completed_lengths = []
        return metrics

    def get_weights(self) -> Any:
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        pass
