"""MultiAgentEnvRunner — samples a MultiAgentEnv into per-MODULE batches.

Equivalent of the reference's MultiAgentEnvRunner + MultiAgentEpisode
(reference: rllib/env/multi_agent_env_runner.py,
rllib/env/multi_agent_episode.py): agents are routed to RLModules by the
config's policy_mapping_fn; each module forwards ONCE per step over the
stacked observations of the agents it controls; per-agent trajectories
get their own GAE and land in their module's batch. Runs complete
episodes (the reference's complete_episodes batch mode) so bootstraps
only matter at truncation."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env.env_runner import EnvRunner
from ray_tpu.rllib.utils.postprocessing import compute_gae


def agent_for_policy(env, mapping, module_id: str) -> str:
    """Representative agent for a policy id, with a CLEAR error when the
    mapping covers no agent (a bare next() would raise StopIteration)."""
    for a in env.possible_agents:
        if mapping(a) == module_id:
            return a
    raise ValueError(
        f"no agent in {env.possible_agents} maps to policy {module_id!r} "
        "under the configured policy_mapping_fn"
    )


class MultiAgentEnvRunner(EnvRunner):
    def __init__(self, config, worker_index: int = 0):
        import jax

        self.config = config
        self.worker_index = worker_index
        self._jax = jax
        env_maker = config.env if callable(config.env) else None
        if env_maker is None:
            raise ValueError("multi-agent config.env must be a callable returning a MultiAgentEnv")
        self.env = env_maker(config.env_config) if config.env_config else env_maker()
        self.mapping: Callable[[str], str] = config.policy_mapping_fn
        # one RLModule per policy id, built against a representative
        # agent's spaces
        self.modules: Dict[str, Any] = {}
        self.params: Dict[str, Any] = {}
        rng = jax.random.PRNGKey(config.seed + 1000 * (worker_index + 1))
        for mid in config.policies:
            agent = agent_for_policy(self.env, self.mapping, mid)
            rng, key = jax.random.split(rng)
            self.modules[mid] = config.build_module(
                self.env.observation_space(agent), self.env.action_space(agent)
            )
            self.params[mid] = self.modules[mid].init_params(key)
        self._rng = rng
        self._weights_seq = 0

        import jax.numpy as jnp

        def make_forward(module):
            def _f(params, obs, rng):
                out = module.forward(params, obs)
                logits = out["logits"]
                action = jax.random.categorical(rng, logits)
                logp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits), action[:, None], axis=1
                )[:, 0]
                return action, logp, out["vf"]

            return jax.jit(_f)

        self._forwards = {mid: make_forward(m) for mid, m in self.modules.items()}
        self._value_fns = {
            mid: jax.jit(lambda p, o, m=m: m.forward(p, o)["vf"])
            for mid, m in self.modules.items()
        }
        self._episode_count = 0
        # per-worker deterministic env seeding (same scheme as the
        # single-agent runner): episode i of worker w reseeds from the
        # stream base so runs reproduce under .debugging(seed=...)
        self._seed_base = config.seed + 10_000 * (worker_index + 1)

    # -- weights --------------------------------------------------------
    def set_weights(self, weights: Dict[str, Any], seq: int = 0):
        for mid, w in weights.items():
            if mid in self.params:
                self.params[mid] = w
        self._weights_seq = seq
        return True

    def get_weights(self):
        return self.params

    # -- sampling -------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        budget = self.config.rollout_fragment_length * max(1, self.config.num_envs_per_env_runner)
        steps = 0
        # per (agent): trajectory columns
        module_rows: Dict[str, Dict[str, List]] = {
            mid: {k: [] for k in ("obs", "actions", "logp_old", "values",
                                  "advantages", "value_targets")}
            for mid in self.modules
        }
        episode_returns: List[float] = []
        episode_lens: List[int] = []

        while steps < budget:
            ep_steps, ep_return = self._run_episode(module_rows)
            steps += ep_steps
            episode_returns.append(ep_return)
            episode_lens.append(ep_steps)
            self._episode_count += 1

        batches = {}
        for mid, cols in module_rows.items():
            if cols["obs"]:
                batches[mid] = {
                    "obs": np.concatenate(cols["obs"], axis=0).astype(np.float32),
                    "actions": np.concatenate(cols["actions"], axis=0),
                    "logp_old": np.concatenate(cols["logp_old"], axis=0).astype(np.float32),
                    "values": np.concatenate(cols["values"], axis=0).astype(np.float32),
                    "advantages": np.concatenate(cols["advantages"], axis=0).astype(np.float32),
                    "value_targets": np.concatenate(cols["value_targets"], axis=0).astype(np.float32),
                }
        metrics = {
            "num_env_steps": steps,
            "episodes_this_iter": len(episode_returns),
            "episode_returns": episode_returns,
            "episode_lens": episode_lens,
            "weights_seq": self._weights_seq,
        }
        return {"batch": batches, "metrics": metrics}

    def _run_episode(self, module_rows):
        env = self.env
        obs, _ = env.reset(seed=self._seed_base + self._episode_count)
        agents = list(env.possible_agents)
        traj = {a: {k: [] for k in ("obs", "act", "logp", "vf", "rew")} for a in agents}
        ep_return = 0.0
        t = 0
        done = False
        while not done:
            # group CURRENT agents by module, forward each module once
            by_module: Dict[str, List[str]] = {}
            for a in obs:
                by_module.setdefault(self.mapping(a), []).append(a)
            actions: Dict[str, Any] = {}
            step_info = {}
            for mid, members in by_module.items():
                stacked = np.stack([np.asarray(obs[a], np.float32) for a in members])
                self._rng, key = self._jax.random.split(self._rng)
                act, logp, vf = self._forwards[mid](self.params[mid], stacked, key)
                act, logp, vf = np.asarray(act), np.asarray(logp), np.asarray(vf)
                for i, a in enumerate(members):
                    actions[a] = act[i].item() if act[i].shape == () else act[i]
                    step_info[a] = (logp[i], vf[i])
            next_obs, rewards, terms, truncs, _ = env.step(actions)
            for a in actions:
                traj[a]["obs"].append(np.asarray(obs[a], np.float32))
                traj[a]["act"].append(actions[a])
                traj[a]["logp"].append(step_info[a][0])
                traj[a]["vf"].append(step_info[a][1])
                traj[a]["rew"].append(float(rewards.get(a, 0.0)))
            ep_return += float(sum(rewards.values()))
            t += 1
            done = terms.get("__all__", False) or truncs.get("__all__", False)
            terminated_all = terms.get("__all__", False)
            obs = next_obs

        # per-agent GAE over the whole episode (terminated → no bootstrap;
        # truncated → bootstrap with V(the agent's final obs) under its
        # module). NOTE the contiguity assumption: an agent's recorded
        # steps are treated as consecutive decisions of ITS trajectory —
        # which holds for agents that act every step they are present;
        # sparse actors would need per-transition next-obs bookkeeping.
        for a, tr in traj.items():
            if not tr["obs"]:
                continue
            mid = self.mapping(a)
            T = len(tr["obs"])
            rew = np.asarray(tr["rew"], np.float32)[None, :]
            vals = np.asarray(tr["vf"], np.float32)[None, :]
            terms_row = np.zeros((1, T), bool)
            terms_row[0, -1] = terminated_all
            dones_row = np.zeros((1, T), bool)
            dones_row[0, -1] = True
            next_vals = np.zeros((1, T), np.float32)
            next_vals[0, :-1] = vals[0, 1:]
            final_obs = obs.get(a)  # absent if the agent left before the end
            if not terminated_all and final_obs is not None:
                final_v = self._value_fns[mid](
                    self.params[mid], np.asarray(final_obs, np.float32)[None]
                )
                next_vals[0, -1] = float(np.asarray(final_v)[0])
            adv, vt = compute_gae(
                rew, vals, next_vals, terms_row, dones_row,
                gamma=self.config.gamma, lambda_=self.config.lambda_,
            )
            rows = module_rows[mid]
            rows["obs"].append(np.stack(tr["obs"]))
            rows["actions"].append(np.asarray(tr["act"]))
            rows["logp_old"].append(np.asarray(tr["logp"], np.float32))
            rows["values"].append(vals[0])
            rows["advantages"].append(adv[0])
            rows["value_targets"].append(vt[0])
        return t, ep_return

    def stop(self) -> None:
        close = getattr(self.env, "close", None)
        if close:
            close()
