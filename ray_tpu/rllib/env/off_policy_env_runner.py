"""OffPolicyEnvRunner — epsilon-greedy transition collection for
value-based algorithms (DQN family).

Counterpart of the reference's SingleAgentEnvRunner when driven by a
DQN config (reference: rllib/env/single_agent_env_runner.py with the
EpsilonGreedy exploration connector,
rllib/connectors/module_to_env/...). Returns flat
(obs, action, reward, next_obs, terminated) transitions; the
autoreset frames of gymnasium>=1.0 vector envs (see
single_agent_env_runner.py for the masking rationale) are dropped.
Epsilon decays linearly against the GLOBAL env-step count, which the
Algorithm pushes down with the weight sync.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.env.env_runner import EnvRunner


class OffPolicyEnvRunner(EnvRunner):
    def __init__(self, config, worker_index: int = 0):
        import jax

        self.config = config
        self.worker_index = worker_index
        self._jax = jax
        from ray_tpu.rllib.utils.env import make_vector_env

        self.env = make_vector_env(config)
        self.num_envs = config.num_envs_per_env_runner
        self.module = config.build_module(
            self.env.single_observation_space, self.env.single_action_space
        )
        self._rng = jax.random.PRNGKey(config.seed + 1000 * (worker_index + 1))
        self.params = self.module.init_params(self._rng)
        self._weights_seq = 0
        self._global_step = 0  # pushed by the Algorithm with sync_weights

        self._q_fn = jax.jit(lambda params, obs: self.module.forward(params, obs)["logits"])
        self._np_rng = np.random.default_rng(config.seed + 77 * (worker_index + 1))

        self._obs, _ = self.env.reset(seed=config.seed + 10_000 * (worker_index + 1))
        self._prev_done = np.zeros((self.num_envs,), dtype=bool)
        self._init_episode_accounting(self.num_envs)

    # -- weights / vars ------------------------------------------------------
    def get_weights(self):
        return self.params

    def set_weights(self, weights, seq: Optional[int] = None, global_step: Optional[int] = None) -> None:
        self.params = self._jax.tree.map(np.asarray, weights)
        if seq is not None:
            self._weights_seq = seq
        if global_step is not None:
            self._global_step = int(global_step)

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._global_step / max(1, c.epsilon_timesteps))
        return float(c.epsilon_initial + frac * (c.epsilon_final - c.epsilon_initial))

    # -- hooks for action-selection variants (SAC's continuous runner
    # subclasses these; the sample loop with its autoreset masking is
    # shared and lives ONLY here) --------------------------------------
    def _on_fragment_start(self) -> None:
        self._eps_now = self._epsilon()

    def _select_actions(self, obs):
        """Returns (stored_action, env_action) for one vector step."""
        q = np.asarray(self._q_fn(self.params, obs.astype(np.float32)))
        action = q.argmax(axis=-1)
        explore = self._np_rng.random(self.num_envs) < self._eps_now
        action = np.where(
            explore, self._np_rng.integers(0, q.shape[-1], size=self.num_envs), action
        ).astype(np.int64)
        return action, action

    def _extra_metrics(self) -> Dict[str, Any]:
        return {"epsilon": self._eps_now}

    # -- sampling ------------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        T = self.config.rollout_fragment_length
        obs_shape = self.env.single_observation_space.shape
        self._on_fragment_start()

        obs_l, act_l, rew_l, next_l, term_l = [], [], [], [], []
        obs = self._obs
        prev_done = self._prev_done
        for _ in range(T):
            action, env_action = self._select_actions(obs)

            next_obs, reward, terminated, truncated, _ = self.env.step(env_action)
            done = terminated | truncated
            live = self._account_step(np.asarray(reward), done, prev_done)
            # keep only real frames (autoreset frames carry a stale action)
            obs_l.append(obs[live].astype(np.float32))
            act_l.append(action[live])
            rew_l.append(np.asarray(reward, np.float32)[live])
            next_l.append(next_obs[live].astype(np.float32))
            term_l.append(np.asarray(terminated, bool)[live])

            obs = next_obs
            prev_done = done
        self._obs = obs
        self._prev_done = prev_done

        batch = {
            "obs": np.concatenate(obs_l).reshape((-1,) + obs_shape),
            "actions": np.concatenate(act_l),
            "rewards": np.concatenate(rew_l),
            "next_obs": np.concatenate(next_l).reshape((-1,) + obs_shape),
            "terminateds": np.concatenate(term_l),
        }
        n = len(batch["actions"])
        self._global_step += n  # local estimate between syncs
        metrics = self._drain_episode_metrics(n, self._weights_seq)
        metrics.update(self._extra_metrics())
        return {"batch": batch, "metrics": metrics}

    def stop(self) -> None:
        self.env.close()
