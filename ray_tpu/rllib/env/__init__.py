from ray_tpu.rllib.env.env_runner import EnvRunner  # noqa: F401
from ray_tpu.rllib.env.single_agent_env_runner import SingleAgentEnvRunner  # noqa: F401

# Native envs this package ships, keyed by registered id. gymnasium's
# registry is PER-PROCESS, so env factories call ensure_registered(id)
# to make driver-registered names resolvable inside remote env-runner
# actors too. New native envs add a row here, nowhere else.
_NATIVE_ENVS = {
    "MinAtarBreakout-v0": "ray_tpu.rllib.env.minatar_breakout",
}


def ensure_registered(env_id) -> None:
    mod = _NATIVE_ENVS.get(env_id) if isinstance(env_id, str) else None
    if mod:
        import importlib

        importlib.import_module(mod).register()
