from ray_tpu.rllib.env.env_runner import EnvRunner  # noqa: F401
from ray_tpu.rllib.env.single_agent_env_runner import SingleAgentEnvRunner  # noqa: F401
