"""MinAtar-style Breakout — a native pixel environment.

The north-star RLlib benchmark is pixel-observation control (reference:
rllib PPO on Atari via ale_py; `ale_py` is not available in this image,
so the pixel task is a MinAtar-style reduction — Young & Tian 2019's
10x10 multi-channel Breakout — implemented here from scratch in numpy).
The observation is a 10x10x4 binary image: channel 0 = paddle, 1 = ball,
2 = ball trail (previous position — makes velocity observable without
frame stacking), 3 = bricks. Actions: 0 = noop, 1 = left, 2 = right.
Reward +1 per brick; the wall respawns when cleared; the episode ends
when the ball passes the paddle.

Exercises the full pixel path: conv encoder (`DiscreteConvModule`),
pixel connectors, and the conv-PPO/DQN learning tests + bench line.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np

SIZE = 10
N_CHANNELS = 4
CH_PADDLE, CH_BALL, CH_TRAIL, CH_BRICK = range(N_CHANNELS)
N_ACTIONS = 3  # noop, left, right
BRICK_ROWS = (1, 2, 3)


class MinAtarBreakout(gym.Env):
    """Gymnasium single env; vectorized via SyncVectorEnv."""

    metadata: Dict[str, Any] = {"render_modes": []}

    def __init__(self, **kwargs):
        self.observation_space = gym.spaces.Box(0.0, 1.0, (SIZE, SIZE, N_CHANNELS), np.float32)
        self.action_space = gym.spaces.Discrete(N_ACTIONS)
        self._rng = np.random.default_rng()
        self._paddle = SIZE // 2
        self._ball: Tuple[int, int] = (3, 0)
        self._prev_ball: Tuple[int, int] = (3, 0)
        self._dy = 1
        self._dx = 1
        self._bricks = np.zeros((SIZE, SIZE), bool)

    # -- helpers -----------------------------------------------------------
    def _spawn_ball(self) -> None:
        x = int(self._rng.integers(0, SIZE))
        self._ball = (3 + 1, x)  # just below the brick wall, moving down
        self._prev_ball = self._ball
        self._dy = 1
        self._dx = 1 if self._rng.random() < 0.5 else -1

    def _obs(self) -> np.ndarray:
        o = np.zeros((SIZE, SIZE, N_CHANNELS), np.float32)
        o[SIZE - 1, self._paddle, CH_PADDLE] = 1.0
        o[self._ball[0], self._ball[1], CH_BALL] = 1.0
        o[self._prev_ball[0], self._prev_ball[1], CH_TRAIL] = 1.0
        o[:, :, CH_BRICK] = self._bricks
        return o

    # -- gym API -----------------------------------------------------------
    def reset(self, *, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._paddle = SIZE // 2
        self._bricks[:] = False
        for r in BRICK_ROWS:
            self._bricks[r, :] = True
        self._spawn_ball()
        return self._obs(), {}

    def step(self, action: int):
        action = int(action)
        if action == 1:
            self._paddle = max(0, self._paddle - 1)
        elif action == 2:
            self._paddle = min(SIZE - 1, self._paddle + 1)

        reward = 0.0
        terminated = False
        y, x = self._ball
        ny, nx = y + self._dy, x + self._dx
        # side walls reflect horizontally
        if nx < 0 or nx >= SIZE:
            self._dx = -self._dx
            nx = x + self._dx
        # ceiling reflects vertically
        if ny < 0:
            self._dy = -self._dy
            ny = y + self._dy
        # brick hit: remove it, score, bounce back up
        if 0 <= ny < SIZE and self._bricks[ny, nx]:
            self._bricks[ny, nx] = False
            reward = 1.0
            self._dy = -self._dy
            ny = y + self._dy
            if not self._bricks.any():
                for r in BRICK_ROWS:
                    self._bricks[r, :] = True
        # paddle row: catch or lose
        if ny >= SIZE - 1:
            if nx == self._paddle or x == self._paddle:
                self._dy = -1
                ny = SIZE - 2
            else:
                terminated = True
                ny = SIZE - 1
        self._prev_ball = (y, x)
        self._ball = (ny, nx)
        return self._obs(), reward, terminated, False, {}

    def render(self):  # pragma: no cover - debugging aid
        chars = np.full((SIZE, SIZE), ".", dtype="<U1")
        chars[self._bricks] = "#"
        chars[self._prev_ball] = "-"
        chars[self._ball] = "o"
        chars[SIZE - 1, self._paddle] = "="
        return "\n".join("".join(row) for row in chars)

    def close(self):
        pass


def register() -> str:
    """Idempotently register `MinAtarBreakout-v0` with gymnasium."""
    import gymnasium as gym

    if "MinAtarBreakout-v0" not in gym.registry:
        gym.register(
            "MinAtarBreakout-v0",
            entry_point="ray_tpu.rllib.env.minatar_breakout:MinAtarBreakout",
            max_episode_steps=500,
        )
    return "MinAtarBreakout-v0"
