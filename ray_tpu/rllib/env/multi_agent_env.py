"""MultiAgentEnv — the dict-keyed multi-agent environment API.

Equivalent of the reference's MultiAgentEnv (reference:
rllib/env/multi_agent_env.py — reset() returns per-agent obs dicts,
step() takes an action dict for the agents that acted and returns
per-agent obs/reward/terminated/truncated dicts with the special
"__all__" key signalling episode end; agents may come and go between
steps)."""
from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

import numpy as np


class MultiAgentEnv:
    """Subclass contract:

    - ``possible_agents``: list of all agent ids.
    - ``observation_spaces`` / ``action_spaces``: dicts keyed by agent id
      (or implement ``observation_space(agent)`` / ``action_space(agent)``).
    - ``reset(seed=None)`` -> (obs_dict, info_dict)
    - ``step(action_dict)`` -> (obs, rewards, terminateds, truncateds,
      infos), each a per-agent dict; terminateds/truncateds carry
      "__all__".
    """

    possible_agents: list = []
    observation_spaces: Dict[str, Any] = {}
    action_spaces: Dict[str, Any] = {}

    def observation_space(self, agent_id):
        return self.observation_spaces[agent_id]

    def action_space(self, agent_id):
        return self.action_spaces[agent_id]

    def reset(self, *, seed: Optional[int] = None) -> Tuple[Dict, Dict]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


class TwoAgentTarget(MultiAgentEnv):
    """Tiny learnable 2-agent env (test fixture, original): each agent
    walks a 1-D line toward its own target; the REWARD IS SHARED (sum of
    both agents' progress), so credit assignment crosses agents — the
    minimal shape that exercises per-agent batches + policy mapping."""

    N = 9  # line length; agents start centered, targets at the ends

    def __init__(self, horizon: int = 32):
        import gymnasium as gym

        self.possible_agents = ["a0", "a1"]
        obs_sp = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
        act_sp = gym.spaces.Discrete(2)  # left / right
        self.observation_spaces = {a: obs_sp for a in self.possible_agents}
        self.action_spaces = {a: act_sp for a in self.possible_agents}
        self.horizon = horizon
        self._rng = np.random.default_rng(0)

    def _obs(self):
        # per-agent: (own position, own target), scaled to [-1, 1]
        return {
            a: np.array(
                [self._pos[a] / (self.N - 1) * 2 - 1, self._target[a] / (self.N - 1) * 2 - 1],
                np.float32,
            )
            for a in self.possible_agents
        }

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        mid = self.N // 2
        self._pos = {"a0": mid, "a1": mid}
        self._target = {
            "a0": int(self._rng.integers(0, 2)) * (self.N - 1),
            "a1": int(self._rng.integers(0, 2)) * (self.N - 1),
        }
        self._t = 0
        return self._obs(), {a: {} for a in self.possible_agents}

    def step(self, action_dict):
        self._t += 1
        shared = 0.0
        for a, act in action_dict.items():
            before = abs(self._pos[a] - self._target[a])
            self._pos[a] = int(np.clip(self._pos[a] + (1 if act == 1 else -1), 0, self.N - 1))
            after = abs(self._pos[a] - self._target[a])
            shared += float(before - after)  # +1 toward the target, -1 away
        done = self._t >= self.horizon or all(
            self._pos[a] == self._target[a] for a in self.possible_agents
        )
        obs = self._obs()
        rewards = {a: shared for a in self.possible_agents}
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {a: {} for a in self.possible_agents}


class TwoAgentContinuousTarget(MultiAgentEnv):
    """Continuous cooperative fixture (original): each agent applies a
    1-D velocity in [-1, 1] to its own point; the SHARED reward is the
    summed progress of both points toward their targets. The minimal
    continuous-control shape for centralized-critic algorithms
    (MADDPG): the optimal joint policy needs both agents moving."""

    def __init__(self, horizon: int = 25):
        import gymnasium as gym

        self.possible_agents = ["a0", "a1"]
        obs_sp = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
        act_sp = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self.observation_spaces = {a: obs_sp for a in self.possible_agents}
        self.action_spaces = {a: act_sp for a in self.possible_agents}
        self.horizon = horizon
        self._rng = np.random.default_rng(0)
        self.step_size = 0.25

    def _obs(self):
        return {
            a: np.array([self._pos[a], self._target[a]], np.float32)
            for a in self.possible_agents
        }

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = {a: 0.0 for a in self.possible_agents}
        self._target = {
            a: float(self._rng.choice([-0.8, 0.8])) for a in self.possible_agents
        }
        self._t = 0
        return self._obs(), {a: {} for a in self.possible_agents}

    def step(self, action_dict):
        self._t += 1
        shared = 0.0
        for a in self.possible_agents:
            act = float(np.clip(np.asarray(action_dict[a]).reshape(-1)[0], -1.0, 1.0))
            before = abs(self._pos[a] - self._target[a])
            self._pos[a] = float(np.clip(self._pos[a] + self.step_size * act, -1.0, 1.0))
            shared += before - abs(self._pos[a] - self._target[a])
        done = self._t >= self.horizon
        obs = self._obs()
        rewards = {a: shared for a in self.possible_agents}
        terms = {a: False for a in self.possible_agents}
        terms["__all__"] = False
        # horizon end is a TRUNCATION: the state isn't terminal, so the
        # critic target must keep bootstrapping through it
        truncs = {a: done for a in self.possible_agents}
        truncs["__all__"] = done
        return obs, rewards, terms, truncs, {a: {} for a in self.possible_agents}
