"""DQN — value-based off-policy learning with replay.

Equivalent of the reference's DQN/DQNConfig
(reference: rllib/algorithms/dqn/dqn.py: training_step samples into an
(optionally prioritized) replay buffer, then runs TD updates at a
sample/train ratio, syncing target nets and runner weights). Epsilon
decays against the global sampled-step count, pushed to runners with
each weight sync.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn.dqn_learner import DQNLearner
from ray_tpu.rllib.env.off_policy_env_runner import OffPolicyEnvRunner


class DQNConfig(AlgorithmConfig):
    learner_class = DQNLearner

    def __init__(self):
        super().__init__()
        self.env_runner_cls = OffPolicyEnvRunner
        self.lr = 5e-4
        self.train_batch_size = 32  # per TD update (replay sample size)
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 120  # in updates
        self.double_q = True
        # n-step returns (reference: dqn n_step / rainbow): >1 swaps the
        # sampler for the Apex n-step runner — the learner consumes the
        # per-row bootstrap discounts it emits
        self.n_step = 1
        self.prioritized_replay = False
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.02
        self.epsilon_timesteps = 10_000
        # TD updates per training_step = sampled_steps * training_intensity / batch
        self.training_intensity = 1.0
        self.rollout_fragment_length = 4
        self.num_envs_per_env_runner = 8


class DQN(Algorithm):
    config_class = DQNConfig

    def __init__(self, config):
        # prioritized replay works with BOTH local and remote learners:
        # LearnerGroup.get_td_errors gathers per-shard TD errors from the
        # lockstep workers and reassembles them in batch order
        # (reference: rllib runs PER under multi-learner setups too,
        # core/learner/learner_group.py:71)
        from ray_tpu.rllib.env.off_policy_env_runner import OffPolicyEnvRunner

        if getattr(config, "n_step", 1) > 1 and config.env_runner_cls is OffPolicyEnvRunner:
            # lazy import: apex_dqn imports this module. Swap the runner
            # on a shallow COPY — mutating the caller's config would make
            # a later rebuild (with n_step set back to 1) silently keep
            # the n-step runner.
            import copy as _copy

            from ray_tpu.rllib.algorithms.apex_dqn.apex_dqn import ApexEnvRunner

            config = _copy.copy(config)
            config.env_runner_cls = ApexEnvRunner
        super().__init__(config)
        from ray_tpu.rllib.utils.replay_buffers import (
            PrioritizedReplayBuffer,
            ReplayBuffer,
        )

        if config.prioritized_replay:
            self.replay = PrioritizedReplayBuffer(
                config.replay_buffer_capacity,
                alpha=config.per_alpha,
                beta=config.per_beta,
                seed=config.seed,
            )
        else:
            self.replay = ReplayBuffer(config.replay_buffer_capacity, seed=config.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config

        # 1. weights + global step (for epsilon) out to the samplers
        self._weights_seq += 1
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights(),
            self._weights_seq,
            global_step=self._env_steps_lifetime,
        )

        # 2. sample one round of fragments into the replay buffer
        samples = self.env_runner_group.sample()
        sampled = 0
        for s in samples:
            if s["batch"] is not None:  # n-step runner may hold partial windows
                if cfg.prioritized_replay and s.get("priorities") is not None:
                    self.replay.add_with_priorities(s["batch"], s["priorities"])
                else:
                    self.replay.add(s["batch"])
            sampled += s["metrics"]["num_env_steps"]

        results = self._fold_sample_metrics(samples)
        results["epsilon"] = samples[0]["metrics"].get("epsilon")

        # 3. TD updates at the configured intensity (stats averaged over
        # all updates this iteration, like the epoch-SGD learners)
        acc: Dict[str, list] = {}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning_starts:
            num_updates = max(1, int(sampled * cfg.training_intensity / cfg.train_batch_size))
            use_per = cfg.prioritized_replay
            for _ in range(num_updates):
                batch = self.replay.sample(cfg.train_batch_size)
                for k, v in self.learner_group.update_once(batch).items():
                    acc.setdefault(k, []).append(v)
                if use_per:
                    td = self.learner_group.get_td_errors()
                    if td is not None:
                        self.replay.update_priorities(td)
        results["learner"] = {k: float(np.mean(v)) for k, v in acc.items()}
        return results


DQNConfig.algo_class = DQN
