"""DQNLearner — double-DQN TD updates with a target network.

Equivalent of the reference's DQN (Rainbow-lite) loss
(reference: rllib/algorithms/dqn/torch/dqn_torch_learner.py): Huber TD
loss, double-Q action selection from the online net, targets from a
periodically-synced target net. Jax-native: the whole step — forward
×3, TD target, Huber, grads, adam — is ONE jitted function; the target
net is just a second pytree argument, so syncing it is a pointer copy
of device arrays, not a parameter transfer.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib.core.learner.learner import Learner


class DQNLearner(Learner):
    def __init__(self, config, obs_space=None, action_space=None, mesh=None):
        super().__init__(config, obs_space, action_space, mesh)
        import jax
        import jax.numpy as jnp
        import optax

        self.target_params = jax.tree.map(jnp.asarray, self.params)
        self._updates = 0
        self.td_errors: np.ndarray | None = None
        module, cfg = self.module, config

        def td_and_loss(params, target_params, batch):
            q_all = module.forward(params, batch["obs"])["logits"]
            q = jnp.take_along_axis(q_all, batch["actions"][:, None], axis=1)[:, 0]
            q_next_t = module.forward(target_params, batch["next_obs"])["logits"]
            if cfg.double_q:
                q_next_o = module.forward(params, batch["next_obs"])["logits"]
                next_a = jnp.argmax(q_next_o, axis=-1)
            else:
                next_a = jnp.argmax(q_next_t, axis=-1)
            q_next = jnp.take_along_axis(q_next_t, next_a[:, None], axis=1)[:, 0]
            # n-step producers (APEX) ship a per-row bootstrap discount
            # (gamma**depth — truncation-flushed partial windows have
            # depth < n_step); plain 1-step batches fall back to gamma
            disc = batch["discounts"] if "discounts" in batch else cfg.gamma
            target = batch["rewards"] + disc * (1.0 - batch["terminateds"].astype(jnp.float32)) * q_next
            td = q - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td**2, jnp.abs(td) - 0.5)
            w = batch.get("weights", jnp.ones_like(huber))
            loss = jnp.mean(w * huber)
            stats = {"loss": loss, "mean_q": jnp.mean(q), "mean_td_error": jnp.mean(jnp.abs(td))}
            return loss, (stats, td)

        def _step(params, target_params, opt_state, batch):
            (_, (stats, td)), grads = jax.value_and_grad(td_and_loss, has_aux=True)(
                params, target_params, batch
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, stats, td

        def _grads(params, target_params, batch):
            (_, (stats, td)), grads = jax.value_and_grad(td_and_loss, has_aux=True)(
                params, target_params, batch
            )
            return grads, stats, td

        self._td_step = jax.jit(_step)
        self._td_grads = jax.jit(_grads)

    def _maybe_sync_target(self):
        self._updates += 1
        if self._updates % self.config.target_network_update_freq == 0:
            self.target_params = self.params

    # one TD step per call (replay batches arrive pre-sampled); this IS the
    # single-step contract of Learner.update_once — epoch-SGD update() does
    # not apply to replay-driven TD learning
    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._batch_sharding is not None:
            batch = self._jax.device_put(batch, self._batch_sharding)
        self.params, self.opt_state, stats, td = self._td_step(
            self.params, self.target_params, self.opt_state, batch
        )
        self.td_errors = np.asarray(td)
        self._maybe_sync_target()
        return {k: float(np.asarray(v)) for k, v in stats.items()}

    # lockstep multi-learner path
    def compute_grads(self, batch):
        grads, stats, td = self._td_grads(self.params, self.target_params, batch)
        self.td_errors = np.asarray(td)
        return self._jax.tree.map(np.asarray, grads), {
            k: float(np.asarray(v)) for k, v in stats.items()
        }

    def apply_grads(self, grads) -> None:
        super().apply_grads(grads)
        self._maybe_sync_target()

    # target net rides along in checkpoints
    def get_state(self):
        state = super().get_state()
        state["target_params"] = self._jax.tree.map(np.asarray, self.target_params)
        state["updates"] = self._updates
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        self.target_params = self._jax.tree.map(np.asarray, state["target_params"])
        self._updates = state.get("updates", 0)
