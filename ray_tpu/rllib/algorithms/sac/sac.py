"""SAC — soft actor-critic for continuous control.

Equivalent of the reference's SAC
(reference: rllib/algorithms/sac/sac.py — twin soft Q critics with
polyak-averaged targets, a tanh-squashed Gaussian actor, and learned
entropy temperature alpha). Jax-native: actor, both critics, alpha and
the polyak update compile into ONE jitted TD step; the target nets are
a second pytree argument.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.rllib.core.learner.learner import Learner
from ray_tpu.rllib.core.rl_module import ContinuousMLPModule
from ray_tpu.rllib.env.off_policy_env_runner import OffPolicyEnvRunner


class ContinuousOffPolicyEnvRunner(OffPolicyEnvRunner):
    """Transition collector for Box action spaces: actions come from the
    squashed-Gaussian policy itself (SAC needs no epsilon schedule —
    exploration is the entropy term). Shares the autoreset-masking
    sample loop with the discrete runner; only action selection differs.
    Stored actions are the pre-scaling [-1, 1] squashed values the
    learner's critics expect; the env sees them rescaled to its bounds."""

    def __init__(self, config, worker_index: int = 0):
        super().__init__(config, worker_index)
        self._sample_fn = self._jax.jit(self.module.sample_action)

    def _on_fragment_start(self) -> None:
        self._warmup = self._global_step < self.config.num_steps_sampled_before_learning_starts

    def _select_actions(self, obs):
        self._rng, key = self._jax.random.split(self._rng)
        if self._warmup:  # uniform random until learning starts
            action = np.asarray(
                self._jax.random.uniform(
                    key, (self.num_envs, self.module.act_dim), minval=-1.0, maxval=1.0
                ),
                np.float32,
            )
        else:
            action, _ = self._sample_fn(self.params, obs.astype(np.float32), key)
            action = np.asarray(action, np.float32)
        low, high = self.module.action_low, self.module.action_high
        return action, low + (action + 1.0) * 0.5 * (high - low)

    def _extra_metrics(self) -> Dict[str, Any]:
        return {}


class SACLearner(Learner):
    """Twin-critic soft TD + reparameterized actor + temperature, in one
    jitted step (reference: sac_torch_learner.py split across three
    optimizers; one optax chain per component here)."""

    def __init__(self, config, obs_space=None, action_space=None, mesh=None):
        super().__init__(config, obs_space, action_space, mesh)
        import jax
        import jax.numpy as jnp
        import optax

        self.target_params = jax.tree.map(jnp.asarray, self.params)
        self.log_alpha = jnp.asarray(float(np.log(config.initial_alpha)))
        self._alpha_opt = optax.adam(config.lr)
        self._alpha_opt_state = self._alpha_opt.init(self.log_alpha)
        self._updates = 0
        self.td_errors = None
        module, cfg = self.module, config
        target_entropy = -float(module.act_dim)

        conservative_w = float(getattr(config, "conservative_weight", 0.0) or 0.0)
        cql_n_actions = int(getattr(config, "cql_n_actions", 10))

        def _grads(params, target_params, log_alpha, batch, rng):
            """Gradient phase: every component's grads from one batch —
            separable so lockstep multi-learner averaging can sit between
            this and _apply (the fused local step composes the two)."""
            alpha = jnp.exp(log_alpha)
            k1, k2, k3 = jax.random.split(rng, 3)

            # critic loss: soft Bellman target from the target critics
            next_a, next_logp = module.sample_action(params, batch["next_obs"], k1)
            tq1, tq2 = module.q_values(target_params, batch["next_obs"], next_a)
            soft_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["terminateds"].astype(jnp.float32)) * soft_v
            target = jax.lax.stop_gradient(target)

            def critic_loss(p):
                q1, q2 = module.q_values(p, batch["obs"], batch["actions"])
                loss = 0.5 * jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)
                gap = jnp.zeros(())
                if conservative_w > 0.0:
                    # CQL conservative penalty: push down Q on sampled
                    # actions (uniform + policy) vs up on dataset actions
                    B = batch["obs"].shape[0]
                    ka, kb = jax.random.split(k3)
                    rand_a = jax.random.uniform(
                        ka, (cql_n_actions, B, module.act_dim), minval=-1.0, maxval=1.0
                    )
                    pol_a, pol_logp = jax.vmap(
                        lambda k: module.sample_action(jax.lax.stop_gradient(p), batch["obs"], k)
                    )(jax.random.split(kb, cql_n_actions))
                    def q_of(actions):
                        q1s, q2s = jax.vmap(lambda a: module.q_values(p, batch["obs"], a))(actions)
                        return q1s, q2s
                    rq1, rq2 = q_of(rand_a)
                    pq1, pq2 = q_of(pol_a)
                    # importance-corrected logsumexp (CQL(H); uniform
                    # density = 0.5^d, policy density = exp(logp))
                    log_u = module.act_dim * jnp.log(0.5)
                    cat1 = jnp.concatenate([rq1 - log_u, pq1 - pol_logp], axis=0)
                    cat2 = jnp.concatenate([rq2 - log_u, pq2 - pol_logp], axis=0)
                    lse1 = jax.nn.logsumexp(cat1, axis=0) - jnp.log(2 * cql_n_actions)
                    lse2 = jax.nn.logsumexp(cat2, axis=0) - jnp.log(2 * cql_n_actions)
                    gap = jnp.mean(lse1 - q1) + jnp.mean(lse2 - q2)
                    loss = loss + conservative_w * gap
                return loss, ((q1 - target), gap)

            def actor_loss(p):
                a, logp = module.sample_action(p, batch["obs"], k2)
                q1, q2 = module.q_values(jax.lax.stop_gradient(p), batch["obs"], a)
                return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

            (closs, (td, cql_gap)), cgrads = jax.value_and_grad(critic_loss, has_aux=True)(params)
            (aloss, logp), agrads = jax.value_and_grad(actor_loss, has_aux=True)(params)
            # critics learn from the critic loss, the actor from the actor
            # loss: mask each gradient tree to its component
            grads = {
                "pi": agrads["pi"],
                "q1": cgrads["q1"],
                "q2": cgrads["q2"],
            }

            def alpha_loss(la):
                return -jnp.mean(jnp.exp(la) * jax.lax.stop_gradient(logp + target_entropy))

            _, agrad = jax.value_and_grad(alpha_loss)(log_alpha)
            stats = {
                "critic_loss": closs,
                "actor_loss": aloss,
                "alpha": alpha,
                "mean_q_target": jnp.mean(target),
                "entropy": -jnp.mean(logp),
            }
            if conservative_w > 0.0:
                stats["cql_gap"] = cql_gap
            return grads, agrad, stats, td

        def _apply(params, target_params, opt_state, log_alpha, alpha_opt_state, grads, agrad):
            """Apply phase: deterministic given grads — identical on every
            lockstep learner, so target nets and alpha never diverge."""
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aupd, alpha_opt_state = self._alpha_opt.update(agrad, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, aupd)
            # polyak target update rides in the same compiled step
            target_params = jax.tree.map(
                lambda t, p: (1.0 - cfg.tau) * t + cfg.tau * p, target_params, params
            )
            return params, target_params, opt_state, log_alpha, alpha_opt_state

        def _step(params, target_params, opt_state, log_alpha, alpha_opt_state, batch, rng):
            grads, agrad, stats, td = _grads(params, target_params, log_alpha, batch, rng)
            params, target_params, opt_state, log_alpha, alpha_opt_state = _apply(
                params, target_params, opt_state, log_alpha, alpha_opt_state, grads, agrad
            )
            return params, target_params, opt_state, log_alpha, alpha_opt_state, stats, td

        self._sac_step = jax.jit(_step)
        self._sac_grads = jax.jit(_grads)
        self._sac_apply = jax.jit(_apply)
        self._rng = jax.random.PRNGKey(config.seed + 31)

    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        self._rng, key = jax.random.split(self._rng)
        (
            self.params,
            self.target_params,
            self.opt_state,
            self.log_alpha,
            self._alpha_opt_state,
            stats,
            td,
        ) = self._sac_step(
            self.params, self.target_params, self.opt_state,
            self.log_alpha, self._alpha_opt_state, batch, key,
        )
        self.td_errors = np.asarray(td)
        self._updates += 1
        return {k: float(np.asarray(v)) for k, v in stats.items()}

    # -- lockstep multi-learner path: grads (incl. the temperature grad,
    # packed under "_alpha") are averaged across learners; _apply is
    # deterministic so target nets and alpha stay bit-identical
    def compute_grads(self, batch):
        import jax

        self._rng, key = jax.random.split(self._rng)
        grads, agrad, stats, td = self._sac_grads(
            self.params, self.target_params, self.log_alpha, batch, key
        )
        self.td_errors = np.asarray(td)
        out = self._jax.tree.map(np.asarray, grads)
        out["_alpha"] = np.asarray(agrad)
        return out, {k: float(np.asarray(v)) for k, v in stats.items()}

    def apply_grads(self, grads) -> None:
        grads = dict(grads)
        agrad = grads.pop("_alpha")
        (
            self.params,
            self.target_params,
            self.opt_state,
            self.log_alpha,
            self._alpha_opt_state,
        ) = self._sac_apply(
            self.params, self.target_params, self.opt_state,
            self.log_alpha, self._alpha_opt_state, grads, agrad,
        )
        self._updates += 1

    def get_state(self):
        state = super().get_state()
        state["target_params"] = self._jax.tree.map(np.asarray, self.target_params)
        state["log_alpha"] = float(np.asarray(self.log_alpha))
        state["alpha_opt_state"] = self._jax.tree.map(np.asarray, self._alpha_opt_state)
        state["updates"] = self._updates
        return state

    def set_state(self, state) -> None:
        import jax.numpy as jnp

        super().set_state(state)
        self.target_params = self._jax.tree.map(np.asarray, state["target_params"])
        self.log_alpha = jnp.asarray(state["log_alpha"])
        if "alpha_opt_state" in state:
            self._alpha_opt_state = self._jax.tree.map(jnp.asarray, state["alpha_opt_state"])
        else:  # checkpoint predates alpha-state persistence
            self._alpha_opt_state = self._alpha_opt.init(self.log_alpha)
        self._updates = state.get("updates", 0)


class SACConfig(DQNConfig):
    learner_class = SACLearner

    def __init__(self):
        super().__init__()
        self.env_runner_cls = ContinuousOffPolicyEnvRunner
        self.module_class = ContinuousMLPModule
        self.model_config = {"hidden": (256, 256)}
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.initial_alpha = 1.0
        self.train_batch_size = 256
        self.training_intensity = 1.0
        self.num_steps_sampled_before_learning_starts = 1500
        self.rollout_fragment_length = 8
        self.num_envs_per_env_runner = 4
        self.prioritized_replay = False
        self.grad_clip = None
        # CQL hooks (0 = plain SAC; CQLConfig turns them on)
        self.conservative_weight = 0.0
        self.cql_n_actions = 10


class SAC(DQN):
    """training_step is DQN's (sample → replay → update_once at
    intensity); only the learner and runner differ. num_learners > 0 runs
    lockstep: replay batches shard across learner actors, grads (incl.
    the temperature grad) average, and the deterministic apply phase
    keeps target nets and alpha identical on every learner."""

    config_class = SACConfig


SACConfig.algo_class = SAC
