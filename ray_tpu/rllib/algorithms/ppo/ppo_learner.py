"""PPOLearner — the clipped-surrogate PPO loss, jitted.

Equivalent of the reference's PPOTorchLearner loss
(reference: rllib/algorithms/ppo/torch/ppo_torch_learner.py and
ppo.py:405 training_step). Advantages are normalized per minibatch;
the value head is trained on GAE value targets with optional clipping.
"""
from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.core.learner.learner import Learner


class PPOLearner(Learner):
    def compute_loss(self, params, batch):
        cfg = self.config
        out = self.module.forward(params, batch["obs"])
        logits = out["logits"]
        vf = out["vf"]

        # same log-softmax as the sampler (single_agent_env_runner.py) so
        # logp and logp_old can never drift between formulas
        import jax

        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=1)[:, 0]

        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        ratio = jnp.exp(logp - batch["logp_old"])
        clipped = jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param)
        policy_loss = -jnp.mean(jnp.minimum(ratio * adv, clipped * adv))

        vf_err = (vf - batch["value_targets"]) ** 2
        if cfg.vf_clip_param is not None:
            vf_clipped = batch["values"] + jnp.clip(
                vf - batch["values"], -cfg.vf_clip_param, cfg.vf_clip_param
            )
            vf_err = jnp.maximum(vf_err, (vf_clipped - batch["value_targets"]) ** 2)
        vf_loss = 0.5 * jnp.mean(vf_err)

        probs = jnp.exp(logp_all)
        entropy = -jnp.mean(jnp.sum(probs * logp_all, axis=-1))

        total = policy_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy
        stats = {
            "total_loss": total,
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "kl": jnp.mean(batch["logp_old"] - logp),
        }
        return total, stats
