"""PPO — Proximal Policy Optimization on the new stack.

Equivalent of the reference's PPO/PPOConfig
(reference: rllib/algorithms/ppo/ppo.py:405 training_step): sample
rollout fragments from the EnvRunnerGroup, update the LearnerGroup
with clipped-surrogate minibatch SGD, broadcast fresh weights back to
the runners.
"""
from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo.ppo_learner import PPOLearner


class PPOConfig(AlgorithmConfig):
    learner_class = PPOLearner

    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01


class PPO(Algorithm):
    config_class = PPOConfig

    def training_step(self) -> Dict[str, Any]:
        import numpy as np

        # 1. fresh weights out to the samplers
        self._weights_seq += 1
        self.env_runner_group.sync_weights(self.learner_group.get_weights(), self._weights_seq)

        # 2. collect rollouts until train_batch_size env steps
        samples = []
        collected = 0
        while collected < self.config.train_batch_size:
            round_samples = self.env_runner_group.sample()
            samples.extend(round_samples)
            collected += sum(s["metrics"]["num_env_steps"] for s in round_samples)

        learner_conn = self.learner_connector
        if self.config.is_multi_agent:
            # per-MODULE concat across samples (reference: MultiAgentBatch)
            mids = sorted({m for s in samples for m in s["batch"]})
            batch = {}
            for mid in mids:
                parts = [s["batch"][mid] for s in samples if mid in s["batch"]]
                keys = parts[0].keys()
                b = {k: np.concatenate([p[k] for p in parts], axis=0) for k in keys}
                batch[mid] = learner_conn(b) if learner_conn else b
        else:
            keys = samples[0]["batch"].keys()
            batch = {k: np.concatenate([s["batch"][k] for s in samples], axis=0) for k in keys}
            if learner_conn:
                batch = learner_conn(batch)

        # 3. learn
        learner_stats = self.learner_group.update(batch)

        results = self._fold_sample_metrics(samples)
        results["learner"] = learner_stats
        return results


PPOConfig.algo_class = PPO
