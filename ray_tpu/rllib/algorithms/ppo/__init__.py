from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo.ppo_learner import PPOLearner  # noqa: F401
