"""APPO — asynchronous PPO: the PPO clipped surrogate on v-trace targets.

Equivalent of the reference's APPO
(reference: rllib/algorithms/appo/appo.py — IMPALA's architecture with
PPO's clip objective). Shares IMPALA's runner path (time-major
sequences, one-generation-stale weights), v-trace and value/entropy
terms; only the policy term differs — a clipped importance-ratio
surrogate, which tolerates re-epoching over the batch.
"""
from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig, IMPALALearner


class APPOLearner(IMPALALearner):
    def _pg_loss(self, target_logp, behavior_logp, pg_adv, valid, n):
        cfg = self.config
        ratio = jnp.exp(target_logp - behavior_logp)
        surr = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param) * pg_adv,
        )
        return -jnp.sum(surr * valid) / n


class APPOConfig(IMPALAConfig):
    learner_class = APPOLearner

    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.num_epochs = 2  # the clip objective tolerates re-epoching
        self.minibatch_size = 32  # sequences per minibatch


class APPO(IMPALA):
    config_class = APPOConfig


APPOConfig.algo_class = APPO
