"""R2D2 — recurrent replay distributed DQN.

Equivalent of the reference's R2D2
(reference: rllib/algorithms/r2d2/r2d2.py — Kapturowski et al.: an
LSTM Q-network trained on replayed SEQUENCES, with a burn-in prefix
that rebuilds the recurrent state before the TD portion so stale
stored states don't poison the gradients; double-Q targets computed
along the same unrolled sequence).

Jax-native: the LSTM cell is an explicit pytree and the whole update
— burn-in unroll (stop-gradient), train unroll, target-net unroll,
double-Q TD, adam — is one jitted `lax.scan` program. Sequences come
from a lane-strided flat ring (the DreamerV3 replay layout); episode
starts inside a window reset the carried state via the stored `first`
flags, and windows never straddle the ring's write head.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dreamerv3.dreamerv3 import _dense, _dense_init, _mlp, _mlp_init
from ray_tpu.rllib.utils.env import env_spaces


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.gamma = 0.997
        self.hidden = 64          # pre-LSTM dense width
        self.lstm_size = 64
        self.burn_in = 8
        self.train_len = 16       # TD steps after burn-in
        self.train_batch_size_seqs = 32
        self.replay_capacity = 100_000
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.02
        self.epsilon_timesteps = 10_000
        self.target_network_update_freq = 200
        self.num_steps_sampled_before_learning_starts = 1000
        self.updates_per_iter = 8
        self.rollout_fragment_length = 64
        self.num_envs_per_env_runner = 4


class LSTMQNet:
    """Dense -> LSTM -> Q head as explicit pytrees."""

    def __init__(self, obs_dim: int, n_actions: int, cfg: R2D2Config):
        self.obs_dim = obs_dim
        self.n_actions = n_actions
        self.hidden = cfg.hidden
        self.lstm = cfg.lstm_size

    def init_params(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        H, L = self.hidden, self.lstm
        return {
            "enc": _mlp_init(k1, (self.obs_dim,), H),
            "lstm_x": _dense_init(k2, H, 4 * L),
            "lstm_h": _dense_init(k3, L, 4 * L),
            "head": _mlp_init(k4, (L, H), self.n_actions),
        }

    def cell(self, p, carry, x):
        """One LSTM step: carry = (h, c), x = encoded obs."""
        h, c = carry
        gates = _dense(p["lstm_x"], x) + _dense(p["lstm_h"], h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f + 1.0), jax.nn.sigmoid(o)
        c = f * c + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return (h, c)

    def step(self, p, carry, obs, first):
        """Encode one obs and advance the state; `first` resets carry."""
        h, c = carry
        mask = (1.0 - first)[:, None]
        carry = (h * mask, c * mask)
        x = jax.nn.silu(_mlp(p["enc"], obs))
        carry = self.cell(p, carry, x)
        q = _mlp(p["head"], carry[0])
        return carry, q

    def unroll(self, p, carry, obs_seq, first_seq):
        """obs_seq [B,L,D], first_seq [B,L] -> q [B,L,A], final carry."""
        def f(carry, t):
            carry, q = self.step(p, carry, obs_seq[:, t], first_seq[:, t])
            return carry, q

        carry, qs = jax.lax.scan(f, carry, jnp.arange(obs_seq.shape[1]))
        return qs.swapaxes(0, 1), carry

    def zero_state(self, batch: int):
        return (jnp.zeros((batch, self.lstm)), jnp.zeros((batch, self.lstm)))


class R2D2(Algorithm):
    config_class = R2D2Config

    def __init__(self, config: R2D2Config):
        import optax

        self.config = config
        self.env_runner_group = None
        self.learner_group = None
        self._iteration = 0
        self._weights_seq = 0
        self._env_steps_lifetime = 0
        self._recent_returns: List[float] = []
        self._spaces = env_spaces(config)
        obs_dim = int(np.prod(self._spaces[0].shape))
        self.net = LSTMQNet(obs_dim, int(self._spaces[1].n), config)
        cfg = config

        rng = jax.random.PRNGKey(cfg.seed)
        k_net, self._rng = jax.random.split(rng)
        self.params = self.net.init_params(k_net)
        self.target_params = jax.tree.map(jnp.asarray, self.params)
        self._opt = optax.chain(optax.clip_by_global_norm(40.0), optax.adam(cfg.lr))
        self._opt_state = self._opt.init(self.params)
        self._updates = 0

        # lane-strided flat ring (DreamerV3 layout); capacity must be a
        # lane multiple or wrap-around indexing interleaves env lanes.
        # Kept on self (never mutate the caller's config); floored to one
        # full lane row so a tiny debug capacity can't truncate to zero.
        n_env = cfg.num_envs_per_env_runner
        self._replay_cap = max(n_env, cfg.replay_capacity - cfg.replay_capacity % n_env)
        self._replay: Dict[str, np.ndarray] = {}
        self._replay_next = 0
        self._replay_size = 0
        self._np_rng = np.random.default_rng(cfg.seed)

        self._build_fns()
        self._build_env()

    # ---------------- env interaction -------------------------------------
    def _build_env(self):
        from ray_tpu.rllib.utils.env import make_same_step_vector_env

        cfg = self.config
        # SAME_STEP autoreset keeps fabricated frames out of the
        # lane-strided ring — see make_same_step_vector_env
        self._env = make_same_step_vector_env(cfg)
        obs, _ = self._env.reset(seed=cfg.seed)
        n = cfg.num_envs_per_env_runner
        self._obs = np.asarray(obs, np.float32).reshape(n, -1)
        self._carry = self.net.zero_state(n)
        self._first = np.ones(n, np.float32)
        self._ep_ret = np.zeros(n, np.float64)

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_lifetime / max(1, cfg.epsilon_timesteps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    def _collect(self, steps: int) -> int:
        cfg = self.config
        n = cfg.num_envs_per_env_runner
        eps = self._epsilon()
        for _ in range(steps):
            self._carry, q = self._step_jit(
                self.params, self._carry, jnp.asarray(self._obs), jnp.asarray(self._first)
            )
            greedy = np.asarray(jnp.argmax(q, -1))
            explore = self._np_rng.random(n) < eps
            action = np.where(
                explore, self._np_rng.integers(0, self.net.n_actions, n), greedy
            ).astype(np.int64)
            next_obs, reward, term, trunc, _ = self._env.step(action)
            done = np.asarray(term) | np.asarray(trunc)
            self._ep_ret += np.asarray(reward)
            self._replay_add({
                "obs": self._obs,
                "action": action,
                "reward": np.asarray(reward, np.float32),
                "term": np.asarray(term, np.float32),
                "first": self._first.astype(np.float32),
            })
            for i in np.nonzero(done)[0]:
                self._recent_returns.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._recent_returns = self._recent_returns[-100:]
            self._obs = np.asarray(next_obs, np.float32).reshape(n, -1)
            self._first = done.astype(np.float32)
            self._env_steps_lifetime += n
        return steps * n

    # ---------------- sequence replay (lane-strided ring) -----------------
    def _replay_add(self, rows: Dict[str, np.ndarray]) -> None:
        cap = self._replay_cap
        nrows = len(rows["reward"])
        if not self._replay:
            for k, v in rows.items():
                self._replay[k] = np.zeros((cap,) + v.shape[1:], v.dtype)
        idx = (self._replay_next + np.arange(nrows)) % cap
        for k, v in rows.items():
            self._replay[k][idx] = v
        self._replay_next = int((self._replay_next + nrows) % cap)
        self._replay_size = int(min(self._replay_size + nrows, cap))

    def _sample_seqs(self, batch: int, length: int) -> Dict[str, np.ndarray]:
        n_env = self.config.num_envs_per_env_runner
        cap = self._replay_cap
        span = length * n_env
        hi = self._replay_size - span
        starts = self._np_rng.integers(0, max(1, hi), size=batch)
        starts = starts - (starts % n_env)
        base = self._replay_next if self._replay_size == cap else 0
        lane = self._np_rng.integers(0, n_env, size=batch)
        idx = (base + starts[:, None] + lane[:, None] + n_env * np.arange(length)[None, :]) % cap
        return {k: v[idx] for k, v in self._replay.items()}

    # ---------------- jitted update ----------------------------------------
    def _build_fns(self):
        import optax

        cfg = self.config
        net = self.net
        B_in = cfg.burn_in

        self._step_jit = jax.jit(net.step)

        # invertible value rescaling (reference: rllib R2D2 lineage,
        # Kapturowski et al. §2.3): Q-nets predict h(value), compressing
        # the ~1/(1-gamma) return scale so the MSE stays conditioned
        eps = 1e-3

        def h(x):
            return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x

        def h_inv(y):
            return jnp.sign(y) * (
                ((jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(y) + 1.0 + eps)) - 1.0)
                 / (2.0 * eps)) ** 2 - 1.0
            )

        def loss_fn(params, target_params, seq):
            # sequence layout: [B, burn_in + train_len + 1] (the +1 step
            # provides the bootstrap target for the last train step)
            obs, first = seq["obs"], seq["first"]
            B = obs.shape[0]
            zero = net.zero_state(B)
            # burn-in: rebuild recurrent state, no gradients
            if B_in > 0:
                _, carry = net.unroll(params, zero, obs[:, :B_in], first[:, :B_in])
                carry = jax.lax.stop_gradient(carry)
                _, t_carry = net.unroll(target_params, zero, obs[:, :B_in], first[:, :B_in])
            else:
                carry = t_carry = zero
            q_seq, _ = net.unroll(params, carry, obs[:, B_in:], first[:, B_in:])
            t_seq, _ = net.unroll(target_params, t_carry, obs[:, B_in:], first[:, B_in:])
            # TD over steps [0, L-1] of the post-burn-in window; step t's
            # bootstrap uses t+1 — invalid when t+1 starts a new episode
            # or the transition terminated
            a = seq["action"][:, B_in:-1]
            r = seq["reward"][:, B_in:-1]
            term = seq["term"][:, B_in:-1]
            next_first = first[:, B_in + 1:]
            q_sa = jnp.take_along_axis(q_seq[:, :-1], a[..., None], -1)[..., 0]
            next_a = jnp.argmax(q_seq[:, 1:], -1)  # double-Q: online picks
            q_next = jnp.take_along_axis(t_seq[:, 1:], next_a[..., None], -1)[..., 0]
            # a next-step episode boundary invalidates the bootstrap
            # UNLESS the transition terminated (then it contributes 0)
            valid = 1.0 - (next_first * (1.0 - term))
            target = h(r + cfg.gamma * (1.0 - term) * h_inv(q_next))
            td = (q_sa - jax.lax.stop_gradient(target)) * valid
            loss = jnp.mean(td**2)
            return loss, {"loss": loss, "mean_q": jnp.mean(q_sa)}

        def update(params, target_params, opt_state, seq):
            (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, seq
            )
            upd, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, upd), opt_state, stats

        self._update = jax.jit(update)

    # ---------------- training loop ----------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        sampled = self._collect(cfg.rollout_fragment_length)
        stats: Dict[str, float] = {}
        if self._replay_size >= cfg.num_steps_sampled_before_learning_starts:
            L = cfg.burn_in + cfg.train_len + 1
            for _ in range(cfg.updates_per_iter):
                seq = self._sample_seqs(cfg.train_batch_size_seqs, L)
                self.params, self._opt_state, st = self._update(
                    self.params, self.target_params, self._opt_state, seq
                )
                self._updates += 1
                if self._updates % cfg.target_network_update_freq == 0:
                    self.target_params = self.params
            stats = {k: float(v) for k, v in st.items()}
        ret = float(np.mean(self._recent_returns)) if self._recent_returns else float("nan")
        return {
            "episode_return_mean": ret,
            "num_env_steps": sampled,
            "epsilon": self._epsilon(),
            "replay_size": self._replay_size,
            "learner": stats,
        }

    def compute_single_action(self, obs, explore: bool = False):
        if not hasattr(self, "_eval_carry") or self._eval_carry is None:
            self._eval_carry = self.net.zero_state(1)
            self._eval_first = np.ones(1, np.float32)
        self._eval_carry, q = self._step_jit(
            self.params, self._eval_carry,
            jnp.asarray(obs, jnp.float32).reshape(1, -1), jnp.asarray(self._eval_first),
        )
        self._eval_first = np.zeros(1, np.float32)
        return int(np.asarray(jnp.argmax(q, -1))[0])

    def reset_eval_state(self) -> None:
        self._eval_carry = None

    def stop(self) -> None:
        self._env.close()


R2D2Config.algo_class = R2D2
