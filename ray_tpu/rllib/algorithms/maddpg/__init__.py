from ray_tpu.rllib.algorithms.maddpg.maddpg import MADDPG, MADDPGConfig  # noqa: F401
