"""MADDPG — multi-agent DDPG with centralized critics.

Equivalent of the reference's MADDPG
(reference: rllib/algorithms/maddpg/maddpg.py — Lowe et al.:
decentralized deterministic actors pi_i(o_i), centralized critics
Q_i(o_all, a_all) trained off joint replay; target actors feed the
critic targets, so each agent's training sees the others' policies
and the nonstationarity of independent learners disappears).

Jax-native: per-agent actor/critic pytrees, one jitted update that
scans nothing — the agent set is static, so the joint concatenation
and the per-agent losses unroll at trace time into a single XLA
program. The env is driven driver-locally over the MultiAgentEnv dict
API (like the reference's old-stack MADDPG, which was also a
single-learner algorithm)."""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dreamerv3.dreamerv3 import _mlp, _mlp_init


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.gamma = 0.95
        self.tau = 0.01
        self.hidden = (64, 64)
        self.train_batch_size = 256
        self.replay_capacity = 100_000
        self.exploration_noise = 0.3
        self.noise_decay_steps = 15_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.updates_per_iter = 16
        self.rollout_steps_per_iter = 100


class MADDPG(Algorithm):
    config_class = MADDPGConfig

    def __init__(self, config: MADDPGConfig):
        import optax

        self.config = config
        self.env_runner_group = None
        self.learner_group = None
        self._iteration = 0
        self._weights_seq = 0
        self._env_steps_lifetime = 0
        self._recent_returns: List[float] = []
        env_cls = config.env
        self._env = env_cls(**(config.env_config or {})) if isinstance(env_cls, type) else env_cls
        self.agents = list(self._env.possible_agents)
        self.obs_dims = {
            a: int(np.prod(self._env.observation_space(a).shape)) for a in self.agents
        }
        self.act_dims = {
            a: int(np.prod(self._env.action_space(a).shape)) for a in self.agents
        }
        joint_obs = sum(self.obs_dims.values())
        joint_act = sum(self.act_dims.values())
        cfg = config

        rng = jax.random.PRNGKey(cfg.seed)
        self._rng, *keys = jax.random.split(rng, 1 + 2 * len(self.agents))
        self.actors = {}
        self.critics = {}
        for i, a in enumerate(self.agents):
            self.actors[a] = _mlp_init(
                keys[2 * i], (self.obs_dims[a],) + tuple(cfg.hidden), self.act_dims[a], out_scale=0.01
            )
            self.critics[a] = _mlp_init(
                keys[2 * i + 1], (joint_obs + joint_act,) + tuple(cfg.hidden), 1, out_scale=0.1
            )
        self.target_actors = jax.tree.map(jnp.asarray, self.actors)
        self.target_critics = jax.tree.map(jnp.asarray, self.critics)

        self._actor_opt = optax.adam(cfg.actor_lr)
        self._critic_opt = optax.adam(cfg.critic_lr)
        self._actor_opt_state = {a: self._actor_opt.init(self.actors[a]) for a in self.agents}
        self._critic_opt_state = {a: self._critic_opt.init(self.critics[a]) for a in self.agents}

        # joint replay: per-agent obs/act/next_obs + shared reward/done
        self._replay: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._np_rng = np.random.default_rng(cfg.seed)

        self._build_update()
        self._act_jit = jax.jit(self._act_all)
        self._obs_now, _ = self._env.reset(seed=cfg.seed)
        self._ep_ret = 0.0

    # ---------------- policies -------------------------------------------
    def _act_all(self, actors, obs_dict):
        return {a: jnp.tanh(_mlp(actors[a], obs_dict[a])) for a in self.agents}

    # ---------------- replay ---------------------------------------------
    def _add(self, row: Dict[str, np.ndarray]) -> None:
        cap = self.config.replay_capacity
        if not self._replay:
            for k, v in row.items():
                self._replay[k] = np.zeros((cap,) + np.asarray(v).shape, np.float32)
        i = self._next
        for k, v in row.items():
            self._replay[k][i] = v
        self._next = (i + 1) % cap
        self._size = min(self._size + 1, cap)

    def _sample(self, n: int) -> Dict[str, jnp.ndarray]:
        idx = self._np_rng.integers(0, self._size, size=n)
        return {k: jnp.asarray(v[idx]) for k, v in self._replay.items()}

    # ---------------- jitted update --------------------------------------
    def _build_update(self):
        import optax

        cfg = self.config
        agents = self.agents

        def joint(batch, prefix):
            return jnp.concatenate([batch[f"{prefix}_{a}"] for a in agents], -1)

        def update(actors, critics, t_actors, t_critics, a_states, c_states, batch):
            obs_all = joint(batch, "obs")
            act_all = joint(batch, "act")
            next_obs_all = joint(batch, "nobs")
            # target joint action from the TARGET actors
            next_act_all = jnp.concatenate(
                [jnp.tanh(_mlp(t_actors[a], batch[f"nobs_{a}"])) for a in agents], -1
            )
            stats = {}
            new_actors, new_critics = {}, {}
            new_a_states, new_c_states = {}, {}
            for a in agents:
                q_next = _mlp(t_critics[a], jnp.concatenate([next_obs_all, next_act_all], -1))[..., 0]
                y = batch["reward"] + cfg.gamma * (1.0 - batch["done"]) * q_next
                y = jax.lax.stop_gradient(y)

                def critic_loss(cp):
                    q = _mlp(cp, jnp.concatenate([obs_all, act_all], -1))[..., 0]
                    return jnp.mean((q - y) ** 2)

                closs, cgrad = jax.value_and_grad(critic_loss)(critics[a])
                cupd, c_state = self._critic_opt.update(cgrad, c_states[a], critics[a])
                new_critics[a] = optax.apply_updates(critics[a], cupd)
                new_c_states[a] = c_state

                def actor_loss(ap):
                    # replace only agent a's action with its current policy
                    acts = [
                        jnp.tanh(_mlp(ap, batch[f"obs_{b}"])) if b == a else batch[f"act_{b}"]
                        for b in agents
                    ]
                    q = _mlp(
                        jax.lax.stop_gradient(new_critics[a]),
                        jnp.concatenate([obs_all, jnp.concatenate(acts, -1)], -1),
                    )[..., 0]
                    return -jnp.mean(q)

                aloss, agrad = jax.value_and_grad(actor_loss)(actors[a])
                aupd, a_state = self._actor_opt.update(agrad, a_states[a], actors[a])
                new_actors[a] = optax.apply_updates(actors[a], aupd)
                new_a_states[a] = a_state
                stats[f"critic_loss_{a}"] = closs
                stats[f"actor_loss_{a}"] = aloss
            t_actors = jax.tree.map(
                lambda t, p: (1 - cfg.tau) * t + cfg.tau * p, t_actors, new_actors
            )
            t_critics = jax.tree.map(
                lambda t, p: (1 - cfg.tau) * t + cfg.tau * p, t_critics, new_critics
            )
            return new_actors, new_critics, t_actors, t_critics, new_a_states, new_c_states, stats

        self._update = jax.jit(update)

    # ---------------- training loop --------------------------------------
    def _noise_scale(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_lifetime / max(1, cfg.noise_decay_steps))
        return cfg.exploration_noise * (1.0 - 0.9 * frac)

    def _collect(self, steps: int) -> int:
        cfg = self.config
        for _ in range(steps):
            obs_j = {a: jnp.asarray(self._obs_now[a], jnp.float32) for a in self.agents}
            acts = self._act_jit(self.actors, obs_j)
            scale = self._noise_scale()
            action_dict = {
                a: np.clip(
                    np.asarray(acts[a], np.float32)
                    + scale * self._np_rng.normal(size=self.act_dims[a]).astype(np.float32),
                    -1.0, 1.0,
                )
                for a in self.agents
            }
            nobs, rewards, terms, truncs, _ = self._env.step(action_dict)
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            row = {"reward": np.float32(np.mean([rewards[a] for a in self.agents])),
                   "done": np.float32(terms.get("__all__", False) and not truncs.get("__all__", False))}
            for a in self.agents:
                row[f"obs_{a}"] = np.asarray(self._obs_now[a], np.float32)
                row[f"act_{a}"] = np.asarray(action_dict[a], np.float32).reshape(self.act_dims[a])
                row[f"nobs_{a}"] = np.asarray(nobs[a], np.float32)
            self._add(row)
            self._ep_ret += row["reward"]
            self._env_steps_lifetime += 1
            if done:
                self._recent_returns.append(self._ep_ret)
                self._recent_returns = self._recent_returns[-100:]
                self._ep_ret = 0.0
                self._obs_now, _ = self._env.reset()
            else:
                self._obs_now = nobs
        return steps

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        sampled = self._collect(cfg.rollout_steps_per_iter)
        stats: Dict[str, float] = {}
        if self._size >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_iter):
                batch = self._sample(cfg.train_batch_size)
                (self.actors, self.critics, self.target_actors, self.target_critics,
                 self._actor_opt_state, self._critic_opt_state, st) = self._update(
                    self.actors, self.critics, self.target_actors, self.target_critics,
                    self._actor_opt_state, self._critic_opt_state, batch,
                )
            stats = {k: float(v) for k, v in st.items()}
        ret = float(np.mean(self._recent_returns[-20:])) if self._recent_returns else float("nan")
        return {
            "episode_return_mean": ret,
            "num_env_steps": sampled,
            "replay_size": self._size,
            "learner": stats,
        }

    def compute_actions(self, obs_dict) -> Dict[str, np.ndarray]:
        obs_j = {a: jnp.asarray(obs_dict[a], jnp.float32) for a in self.agents}
        return {a: np.asarray(v) for a, v in self._act_jit(self.actors, obs_j).items()}

    def stop(self) -> None:
        pass


MADDPGConfig.algo_class = MADDPG
