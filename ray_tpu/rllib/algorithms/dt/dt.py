"""DT — Decision Transformer (offline RL as sequence modeling).

Equivalent of the reference's DT
(reference: rllib/algorithms/dt/dt.py — Chen et al.: model trajectories
as (return-to-go, state, action) token triplets with a causal
transformer; act at eval time by conditioning on a target return).
Jax-native: the transformer is an explicit-pytree module like the rest
of the stack — embeddings + pre-LN causal attention blocks, jitted
end to end; training runs through the standard Learner minibatch SGD
over sampled context windows.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner.learner import Learner
from ray_tpu.rllib.core.learner.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.utils.env import env_spaces


def _dense_init(rng, n_in, n_out, scale=None):
    if scale is None:
        scale = 1.0 / np.sqrt(n_in)
    w = jax.random.normal(rng, (n_in, n_out), jnp.float32) * scale
    return {"w": w, "b": jnp.zeros((n_out,), jnp.float32)}


def _dense(p, x):
    return x @ p["w"] + p["b"]


def _ln(p, x):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]


def _ln_init(d):
    return {"g": jnp.ones((d,), jnp.float32), "b": jnp.zeros((d,), jnp.float32)}


class DTModule(RLModule):
    """Causal transformer over interleaved (RTG, obs, action) tokens.

    Sequence layout for a K-step context: [R_0 s_0 a_0 R_1 s_1 a_1 ...];
    action logits for step t are read from the *state* token's output
    (position 3t+1), so a_t is predicted from everything up to s_t.
    """

    def __init__(self, obs_space, action_space, model_config=None):
        cfg = dict(model_config or {})
        self.obs_dim = int(np.prod(obs_space.shape))
        self.n_actions = int(action_space.n)
        self.embed_dim = int(cfg.get("embed_dim", 64))
        self.n_layers = int(cfg.get("n_layers", 2))
        self.n_heads = int(cfg.get("n_heads", 2))
        self.context_length = int(cfg.get("context_length", 20))
        self.max_timestep = int(cfg.get("max_timestep", 2048))

    def init_params(self, rng):
        d = self.embed_dim
        keys = jax.random.split(rng, 5 + self.n_layers)
        layers = []
        for i in range(self.n_layers):
            lk = jax.random.split(keys[5 + i], 4)
            layers.append({
                "ln1": _ln_init(d),
                "qkv": _dense_init(lk[0], d, 3 * d),
                "proj": _dense_init(lk[1], d, d, scale=0.02),
                "ln2": _ln_init(d),
                "fc1": _dense_init(lk[2], d, 4 * d),
                "fc2": _dense_init(lk[3], 4 * d, d, scale=0.02),
            })
        return {
            "embed_rtg": _dense_init(keys[0], 1, d),
            "embed_obs": _dense_init(keys[1], self.obs_dim, d),
            "embed_act": jax.random.normal(keys[2], (self.n_actions + 1, d), jnp.float32) * 0.02,
            "embed_t": jax.random.normal(keys[3], (self.max_timestep, d), jnp.float32) * 0.02,
            "layers": layers,
            "ln_f": _ln_init(d),
            "head": _dense_init(keys[4], d, self.n_actions, scale=0.02),
        }

    def _block(self, p, x, mask):
        B, T, d = x.shape
        h = self.n_heads
        y = _ln(p["ln1"], x)
        qkv = _dense(p["qkv"], y).reshape(B, T, 3, h, d // h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,h,hd]
        att = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(d // h)
        att = jnp.where(mask, att, -1e9)
        att = jax.nn.softmax(att, axis=-1)
        y = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, d)
        x = x + _dense(p["proj"], y)
        y = _ln(p["ln2"], x)
        y = _dense(p["fc2"], jax.nn.gelu(_dense(p["fc1"], y)))
        return x + y

    def forward_seq(self, params, rtg, obs, actions, timesteps):
        """rtg [B,K], obs [B,K,D], actions [B,K] int, timesteps [B,K] int
        → action logits [B,K,n_actions] (one per state token)."""
        B, K = rtg.shape
        te = params["embed_t"][jnp.clip(timesteps, 0, self.max_timestep - 1)]  # [B,K,d]
        er = _dense(params["embed_rtg"], rtg[..., None]) + te
        eo = _dense(params["embed_obs"], obs) + te
        ea = params["embed_act"][jnp.clip(actions, 0, self.n_actions)] + te
        # interleave to [B, 3K, d]
        x = jnp.stack([er, eo, ea], axis=2).reshape(B, 3 * K, self.embed_dim)
        T = 3 * K
        causal = jnp.tril(jnp.ones((T, T), bool))[None, None]  # [1,1,T,T]
        for p in params["layers"]:
            x = self._block(p, x, causal)
        x = _ln(params["ln_f"], x)
        state_tok = x.reshape(B, K, 3, self.embed_dim)[:, :, 1]  # output at s_t
        return _dense(params["head"], state_tok)

    # RLModule interface compatibility (single-obs forward is undefined
    # for a sequence model; evaluation goes through DT.evaluate)
    def forward(self, params, obs):
        raise NotImplementedError("DTModule is sequence-conditioned; use forward_seq")


class DTLearner(Learner):
    """Masked cross-entropy over the context window's action tokens."""

    def compute_loss(self, params, batch):
        logits = self.module.forward_seq(
            params, batch["rtg"], batch["obs"], batch["actions"], batch["timesteps"]
        )
        logp = jax.nn.log_softmax(logits)
        tgt = jnp.take_along_axis(logp, batch["actions"][..., None], axis=-1)[..., 0]
        mask = batch["mask"]
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = -(tgt * mask).sum() / denom
        acc = ((jnp.argmax(logits, -1) == batch["actions"]) * mask).sum() / denom
        return loss, {"total_loss": loss, "accuracy": acc}


class DTConfig(AlgorithmConfig):
    learner_class = DTLearner

    def __init__(self):
        super().__init__()
        self.module_class = DTModule
        self.model_config = {"embed_dim": 64, "n_layers": 2, "n_heads": 2, "context_length": 20}
        self.offline_data: Any = None
        self.rtg_scale = 100.0        # returns are divided by this before embedding
        self.target_return = None     # eval conditioning; defaults to best seen
        self.windows_per_iter = 2048  # sampled context windows per train()
        self.lr = 3e-4
        self.minibatch_size = 128
        self.num_epochs = 1

    def offline(self, data=None):
        """data: {"obs": [N,D], "actions": [N], "rewards": [N], "dones": [N]}
        flat transition arrays (episodes split on `dones`), or a list of
        per-episode dicts with those keys."""
        if data is not None:
            self.offline_data = data
        return self

    def copy(self) -> "DTConfig":
        data, self.offline_data = self.offline_data, None
        try:
            out = super().copy()
        finally:
            self.offline_data = data
        out.offline_data = data
        return out


class DT(Algorithm):
    config_class = DTConfig

    def __init__(self, config):
        if config.offline_data is None:
            raise ValueError(
                "DT requires offline episodes: DTConfig().offline({'obs': ..., "
                "'actions': ..., 'rewards': ..., 'dones': ...})"
            )
        self.config = config
        self.env_runner_group = None
        self._spaces = env_spaces(config)
        self.learner_group = LearnerGroup(config, *self._spaces)
        self._iteration = 0
        self._weights_seq = 0
        self._env_steps_lifetime = 0
        self._recent_returns: List[float] = []
        self._episodes = self._segment(config.offline_data)
        self._best_return = max(float(ep["rtg"][0]) for ep in self._episodes)
        self._rng = np.random.default_rng(config.seed)
        self._eval_module = None
        self._act_fn = None

    @staticmethod
    def _segment(data) -> List[Dict[str, np.ndarray]]:
        """Split flat transition arrays into episodes and precompute
        returns-to-go (reverse cumulative rewards)."""
        if isinstance(data, list):
            episodes = [
                {
                    "obs": np.asarray(ep["obs"], np.float32),
                    "actions": np.asarray(ep["actions"], np.int64),
                    "rewards": np.asarray(ep["rewards"], np.float32),
                }
                for ep in data
            ]
        else:
            obs = np.asarray(data["obs"], np.float32)
            act = np.asarray(data["actions"], np.int64)
            rew = np.asarray(data["rewards"], np.float32)
            dones = np.asarray(data["dones"], bool)
            episodes = []
            start = 0
            for i in range(len(dones)):
                if dones[i]:
                    episodes.append({
                        "obs": obs[start : i + 1],
                        "actions": act[start : i + 1],
                        "rewards": rew[start : i + 1],
                    })
                    start = i + 1
            if start < len(dones):
                episodes.append({"obs": obs[start:], "actions": act[start:], "rewards": rew[start:]})
        for ep in episodes:
            ep["rtg"] = np.cumsum(ep["rewards"][::-1])[::-1].astype(np.float32)
        return [ep for ep in episodes if len(ep["actions"]) > 0]

    def _sample_windows(self, n: int) -> Dict[str, np.ndarray]:
        cfg = self.config
        K = int(cfg.model_config.get("context_length", 20))
        D = int(np.prod(self._spaces[0].shape))
        lens = np.asarray([len(ep["actions"]) for ep in self._episodes], np.float64)
        probs = lens / lens.sum()  # sample windows ∝ episode length
        eps = self._rng.choice(len(self._episodes), size=n, p=probs)
        batch = {
            "rtg": np.zeros((n, K), np.float32),
            "obs": np.zeros((n, K, D), np.float32),
            "actions": np.zeros((n, K), np.int64),
            "timesteps": np.zeros((n, K), np.int64),
            "mask": np.zeros((n, K), np.float32),
        }
        for i, e in enumerate(eps):
            ep = self._episodes[e]
            T = len(ep["actions"])
            end = int(self._rng.integers(1, T + 1))  # window covers [end-k, end)
            k = min(K, end)
            sl = slice(end - k, end)
            batch["rtg"][i, K - k :] = ep["rtg"][sl] / cfg.rtg_scale
            batch["obs"][i, K - k :] = ep["obs"][sl].reshape(k, D)
            batch["actions"][i, K - k :] = ep["actions"][sl]
            batch["timesteps"][i, K - k :] = np.arange(end - k, end)
            batch["mask"][i, K - k :] = 1.0
        return batch

    def training_step(self) -> Dict[str, Any]:
        batch = self._sample_windows(self.config.windows_per_iter)
        stats = self.learner_group.update(batch)
        self._weights_seq += 1
        return {
            "learner": stats,
            "episode_return_mean": float("nan"),
            "num_offline_episodes": len(self._episodes),
        }

    def evaluate(self, num_episodes: int = 10, target_return: float = None) -> Dict[str, Any]:
        """Roll out the model conditioned on a target return (defaults to
        the best return in the dataset — 'be as good as the best you saw')."""
        from ray_tpu.rllib.utils.env import make_single_env

        cfg = self.config
        if target_return is None:
            target_return = cfg.target_return if cfg.target_return is not None else self._best_return
        if self._eval_module is None:
            self._eval_module = cfg.build_module(*self._spaces)
            self._act_fn = jax.jit(self._eval_module.forward_seq)
        weights = self.learner_group.get_weights()
        K = self._eval_module.context_length
        D = self._eval_module.obs_dim
        env = make_single_env(cfg)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=2000 + ep)
            rtgs: List[float] = [target_return / cfg.rtg_scale]
            obs_hist: List[np.ndarray] = [np.asarray(obs, np.float32).reshape(D)]
            act_hist: List[int] = []
            total, done, t = 0.0, False, 0
            while not done:
                k = min(K, len(obs_hist))
                b = {
                    "rtg": np.zeros((1, K), np.float32),
                    "obs": np.zeros((1, K, D), np.float32),
                    "actions": np.zeros((1, K), np.int64),
                    "timesteps": np.zeros((1, K), np.int64),
                }
                b["rtg"][0, K - k :] = rtgs[-k:]
                b["obs"][0, K - k :] = np.stack(obs_hist[-k:])
                # a_t not yet taken: pad id at the last slot
                acts = act_hist[-(k - 1) :] + [self._eval_module.n_actions] if k > 1 else [
                    self._eval_module.n_actions
                ]
                b["actions"][0, K - k :] = acts
                b["timesteps"][0, K - k :] = np.arange(t - k + 1, t + 1)
                logits = self._act_fn(weights, b["rtg"], b["obs"], b["actions"], b["timesteps"])
                action = int(jnp.argmax(logits[0, -1]))
                obs, r, term, trunc, _ = env.step(action)
                total += float(r)
                act_hist.append(action)
                rtgs.append(rtgs[-1] - float(r) / cfg.rtg_scale)
                obs_hist.append(np.asarray(obs, np.float32).reshape(D))
                t += 1
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)), "episodes": returns}

    def stop(self) -> None:
        pass


DTConfig.algo_class = DT
