from ray_tpu.rllib.algorithms.bandits.bandits import (  # noqa: F401
    LinTS,
    LinTSConfig,
    LinUCB,
    LinUCBConfig,
)
