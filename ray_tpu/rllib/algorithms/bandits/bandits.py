"""Contextual bandits: LinUCB and linear Thompson sampling.

Equivalent of the reference's bandit algorithms
(reference: rllib/algorithms/bandit/bandit.py — BanditLinUCB /
BanditLinTS over per-arm linear models). Closed-form ridge posteriors
per arm; no env runners or replay — train() consumes batches of
(context, arm, reward) either from an attached offline dataset or from
an interactive `learn_one` loop.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class _LinearArm:
    """Ridge posterior for one arm: A = X'X + lam*I, b = X'y."""

    def __init__(self, dim: int, lam: float = 1.0):
        self.A = np.eye(dim) * lam
        self.b = np.zeros(dim)
        self._dirty = False
        self._Ainv = np.eye(dim) / lam  # (lam*I)^-1 in closed form

    def update(self, x: np.ndarray, reward: float):
        self.A += np.outer(x, x)
        self.b += reward * x
        self._dirty = True

    @property
    def Ainv(self) -> np.ndarray:
        if self._dirty:
            self._Ainv = np.linalg.inv(self.A)
            self._dirty = False
        return self._Ainv

    @property
    def theta(self) -> np.ndarray:
        return self.Ainv @ self.b


class _BanditBase:
    def __init__(self, num_arms: int, context_dim: int, lam: float = 1.0,
                 seed: Optional[int] = None):
        self.num_arms = num_arms
        self.context_dim = context_dim
        self.arms = [_LinearArm(context_dim, lam) for _ in range(num_arms)]
        self._rng = np.random.default_rng(seed)
        self._steps = 0
        self._cum_reward = 0.0

    def learn_one(self, context, arm: int, reward: float) -> None:
        self.arms[arm].update(np.asarray(context, np.float64), float(reward))
        self._steps += 1
        self._cum_reward += float(reward)

    def train_batch(self, batch: Dict[str, Any]) -> Dict[str, float]:
        ctx = np.asarray(batch["context"], np.float64)
        arms = np.asarray(batch["arm"], np.int64)
        rew = np.asarray(batch["reward"], np.float64)
        for x, a, r in zip(ctx, arms, rew):
            self.learn_one(x, int(a), float(r))
        return {"steps": float(self._steps), "mean_reward": self._cum_reward / max(1, self._steps)}

    def stats(self) -> Dict[str, float]:
        return {"steps": float(self._steps),
                "mean_reward": self._cum_reward / max(1, self._steps)}


class LinUCBConfig:
    def __init__(self, num_arms: int, context_dim: int, alpha: float = 1.0,
                 lam: float = 1.0, seed: Optional[int] = None):
        self.num_arms, self.context_dim = num_arms, context_dim
        self.alpha, self.lam, self.seed = alpha, lam, seed

    def build(self) -> "LinUCB":
        return LinUCB(self)


class LinUCB(_BanditBase):
    """Deterministic optimism: pick argmax theta'x + alpha*sqrt(x'Ainv x)."""

    def __init__(self, config: LinUCBConfig):
        super().__init__(config.num_arms, config.context_dim, config.lam, config.seed)
        self.alpha = config.alpha

    def select_arm(self, context) -> int:
        x = np.asarray(context, np.float64)
        scores = [
            float(arm.theta @ x + self.alpha * np.sqrt(max(x @ arm.Ainv @ x, 0.0)))
            for arm in self.arms
        ]
        return int(np.argmax(scores))


class LinTSConfig:
    def __init__(self, num_arms: int, context_dim: int, v: float = 0.5,
                 lam: float = 1.0, seed: Optional[int] = None):
        self.num_arms, self.context_dim = num_arms, context_dim
        self.v, self.lam, self.seed = v, lam, seed

    def build(self) -> "LinTS":
        return LinTS(self)


class LinTS(_BanditBase):
    """Thompson sampling: draw theta ~ N(theta_hat, v^2 Ainv) per arm."""

    def __init__(self, config: LinTSConfig):
        super().__init__(config.num_arms, config.context_dim, config.lam, config.seed)
        self.v = config.v

    def select_arm(self, context) -> int:
        x = np.asarray(context, np.float64)
        scores = []
        for arm in self.arms:
            sample = self._rng.multivariate_normal(arm.theta, self.v**2 * arm.Ainv)
            scores.append(float(sample @ x))
        return int(np.argmax(scores))
