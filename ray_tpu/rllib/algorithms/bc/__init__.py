from ray_tpu.rllib.algorithms.bc.bc import BC, BCConfig  # noqa: F401
