"""BC — Behavior Cloning (offline RL).

Equivalent of the reference's BC algorithm
(reference: rllib/algorithms/bc/bc.py — supervised learning on expert
(obs, action) pairs through the same RLModule/Learner stack as the
online algorithms; a BCConfig.offline_data dataset replaces the
EnvRunnerGroup sampling loop).
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner.learner import Learner


class BCLearner(Learner):
    """Negative log-likelihood of expert actions under the policy."""

    def compute_loss(self, params, batch):
        import jax

        out = self.module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(out["logits"])
        logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=1)[:, 0]
        loss = -jnp.mean(logp)
        probs = jnp.exp(logp_all)
        entropy = -jnp.mean(jnp.sum(probs * logp_all, axis=-1))
        accuracy = jnp.mean((jnp.argmax(out["logits"], axis=-1) == batch["actions"]).astype(jnp.float32))
        return loss, {"total_loss": loss, "entropy": entropy, "accuracy": accuracy}


class BCConfig(AlgorithmConfig):
    learner_class = BCLearner

    def __init__(self):
        super().__init__()
        self.offline_data: Dict[str, Any] = {}  # {"obs": [N, ...], "actions": [N]}
        self.num_epochs = 1

    def offline(self, data=None):
        """data: {"obs": array, "actions": array} expert transitions, or a
        ray_tpu.data Dataset with those columns."""
        if data is not None:
            self.offline_data = data
        return self

    def copy(self) -> "BCConfig":
        # the dataset may be huge: share it by reference instead of
        # deep-copying it through build() (and pickling it into every
        # checkpoint via save_to_path)
        data, self.offline_data = self.offline_data, {}
        try:
            out = super().copy()
        finally:
            self.offline_data = data
        out.offline_data = data
        return out


class BC(Algorithm):
    config_class = BCConfig

    def __init__(self, config):
        from ray_tpu.rllib.core.learner.learner_group import LearnerGroup
        from ray_tpu.rllib.utils.env import env_spaces

        data = config.offline_data
        if not (hasattr(data, "iter_batches") or ("obs" in data and "actions" in data)):
            raise ValueError(
                "BC requires expert data: BCConfig().offline({'obs': ..., 'actions': ...}) "
                "or a ray_tpu.data Dataset with those columns"
            )
        # offline: no env stepping — spaces come from the env spec; the
        # base Algorithm bookkeeping (_iteration, _weights_seq, inference
        # cache contract) is shared, only the sampling side is replaced
        self.config = config
        self.env_runner_group = None
        self._spaces = env_spaces(config)
        self.learner_group = LearnerGroup(config, *self._spaces)
        self._iteration = 0
        self._weights_seq = 0
        self._env_steps_lifetime = 0
        self._recent_returns: list = []
        if hasattr(data, "iter_batches"):  # a ray_tpu.data Dataset
            obs_parts, act_parts = [], []
            for b in data.iter_batches(batch_size=4096, batch_format="numpy"):
                obs_parts.append(np.asarray(b["obs"]))
                act_parts.append(np.asarray(b["actions"]))
            data = {"obs": np.concatenate(obs_parts), "actions": np.concatenate(act_parts)}
        self._batch = {
            "obs": np.asarray(data["obs"], dtype=np.float32),
            "actions": np.asarray(data["actions"], dtype=np.int64),
        }
        self._eval_module = None

    def training_step(self) -> Dict[str, Any]:
        stats = self.learner_group.update(self._batch)
        self._weights_seq += 1  # inference caches invalidate per train()
        return {"learner": stats, "episode_return_mean": float("nan"),
                "num_offline_samples": len(self._batch["actions"])}

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import time

        if getattr(self, "_infer_cache_seq", None) != self._weights_seq:
            if self._eval_module is None:
                self._eval_module = self.config.build_module(*self._spaces)
            self._infer_weights = self.learner_group.get_weights()
            self._infer_cache_seq = self._weights_seq
        out = self._eval_module.forward(self._infer_weights, jnp.asarray(obs, dtype=jnp.float32)[None])
        if explore:
            key = jax.random.PRNGKey(int(time.monotonic_ns() % (2**31)))
            return int(jax.random.categorical(key, out["logits"])[0])
        return int(jnp.argmax(out["logits"], axis=-1)[0])

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Greedy rollouts of the cloned policy."""
        from ray_tpu.rllib.utils.env import make_single_env

        env = make_single_env(self.config)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=1000 + ep)
            total, done = 0.0, False
            while not done:
                action = self.compute_single_action(obs)
                obs, r, term, trunc, _ = env.step(action)
                total += float(r)
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)), "episodes": returns}

    def stop(self) -> None:
        self.learner_group.stop()


BCConfig.algo_class = BC
