"""DDPG — deep deterministic policy gradients.

Equivalent of the reference's DDPG
(reference: rllib/algorithms/ddpg/ddpg.py — deterministic actor +
single Q critic with target networks and Ornstein-Uhlenbeck/Gaussian
exploration noise). Here DDPG is TD3 with the three TD3 additions
turned off: one critic (twin_q=False), no target policy smoothing
(target_noise=0), and an actor update every step (policy_delay=1) —
which is exactly how the two algorithms relate in the literature, and
keeps one jitted learner path for both.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.td3.td3 import TD3, TD3Config, TD3EnvRunner


class DDPGEnvRunner(TD3EnvRunner):
    """Ornstein-Uhlenbeck exploration noise (reference:
    rllib/utils/exploration/ornstein_uhlenbeck_noise.py) — temporally
    correlated noise suits momentum-driven continuous-control envs;
    plain Gaussian (TD3's choice) is available via ou_theta=1, ou_sigma.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._ou_state = None
        # persistent generator: reseeding per step from _global_step
        # (constant within a fragment) would freeze the OU increments
        # into a per-fragment bias instead of exploration noise
        self._noise_rng = np.random.default_rng(self.config.seed * 9973 + self.worker_index)

    def _select_actions(self, obs):
        cfg = self.config
        if self._warmup:
            return super()._select_actions(obs)
        self._rng, key = self._jax.random.split(self._rng)
        a, _ = self._sample_fn(self.params, obs.astype(np.float32), key)
        a = np.asarray(a, np.float32)
        if self._ou_state is None or self._ou_state.shape != a.shape:
            self._ou_state = np.zeros_like(a)
        # dx = theta * (mu - x) + sigma * N(0, 1), mu = 0
        self._ou_state = (
            self._ou_state
            + cfg.ou_theta * (0.0 - self._ou_state)
            + cfg.ou_sigma * self._noise_rng.normal(size=a.shape).astype(np.float32)
        )
        action = np.clip(a + cfg.exploration_noise_scale * self._ou_state, -1.0, 1.0)
        low, high = self.module.action_low, self.module.action_high
        return action, low + (action + 1.0) * 0.5 * (high - low)


class DDPGConfig(TD3Config):
    def __init__(self):
        super().__init__()
        self.env_runner_cls = DDPGEnvRunner
        # the three TD3 deltas, reverted:
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0
        # OU exploration
        self.ou_theta = 0.15
        self.ou_sigma = 0.2
        self.exploration_noise_scale = 1.0
        self.tau = 0.005
        self.lr = 1e-3


class DDPG(TD3):
    config_class = DDPGConfig


DDPGConfig.algo_class = DDPG
