"""ARS — Augmented Random Search.

Equivalent of the reference's ARS (reference: rllib/algorithms/ars/ars.py
— Mania et al.'s random-search policy optimizer: antithetic Gaussian
directions like ES, but (1) only the top-k directions by best-of-pair
return contribute to the update, (2) the step is normalized by the
standard deviation of the selected returns, and (3) rollouts whiten
observations with a running mean/std shared across iterations). Shares
the ES task fan-out: every direction evaluates as one task.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.es.es import ES, ESConfig, _flatten, _unflatten

import ray_tpu


@ray_tpu.remote
def _ars_rollout(module_blob, flat_params, env_name, env_config, seed: int,
                 episodes: int, obs_mean, obs_std):
    """Greedy episodes with whitened observations; returns
    (mean return, env steps, obs count, obs sum, obs sumsq) so the
    driver can fold the stats into its running normalizer."""
    import gymnasium as gym
    import jax.numpy as jnp
    import numpy as _np
    import pickle

    module, template = pickle.loads(module_blob)
    params = _unflatten(_np.asarray(flat_params, _np.float32), template)
    mean = _np.asarray(obs_mean, _np.float32)
    std = _np.asarray(obs_std, _np.float32)
    env = gym.make(env_name, **(env_config or {}))
    total, steps = 0.0, 0
    cnt, s1, s2 = 0, _np.zeros_like(mean, _np.float64), _np.zeros_like(mean, _np.float64)
    for ep in range(episodes):
        obs, _ = env.reset(seed=seed + ep)
        done = False
        while not done:
            o = _np.asarray(obs, _np.float32)
            cnt += 1
            s1 += o
            s2 += o.astype(_np.float64) ** 2
            white = (o - mean) / std
            logits = module.forward(params, jnp.asarray(white)[None])["logits"]
            action = int(jnp.argmax(logits, axis=-1)[0])
            obs, r, term, trunc, _ = env.step(action)
            total += float(r)
            steps += 1
            done = term or trunc
    env.close()
    return total / episodes, steps, cnt, s1, s2


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.population = 16           # direction PAIRS per iteration
        self.num_top_directions = 8    # k: directions kept for the update
        self.noise_std = 0.05
        self.ars_lr = 0.05
        self.observation_filter = True  # running obs mean/std whitening


class ARS(ES):
    config_class = ARSConfig

    def __init__(self, config):
        super().__init__(config)
        # running observation normalizer (reference: MeanStdFilter,
        # rllib/utils/filter.py) — folded from rollout-side sufficient
        # statistics, so the driver never sees raw observations
        dim = int(np.prod(self._spaces[0].shape))
        self._obs_count = 0
        self._obs_sum = np.zeros(dim, np.float64)
        self._obs_sumsq = np.zeros(dim, np.float64)

    def _obs_stats(self):
        if not self.config.observation_filter or self._obs_count < 2:
            return np.zeros(self._obs_sum.shape, np.float32), np.ones(self._obs_sum.shape, np.float32)
        mean = self._obs_sum / self._obs_count
        var = np.maximum(self._obs_sumsq / self._obs_count - mean**2, 1e-6)
        return mean.astype(np.float32), np.sqrt(var).astype(np.float32)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n, std = cfg.population, cfg.noise_std
        k = min(cfg.num_top_directions, n)
        mean, sd = self._obs_stats()
        eps = self._rng.standard_normal((n, len(self.theta))).astype(np.float32)
        refs = []
        for i in range(n):
            for sign in (1.0, -1.0):
                refs.append(_ars_rollout.remote(
                    self._module_blob, self.theta + sign * std * eps[i],
                    cfg.env, cfg.env_config,
                    seed=int(self._rng.integers(1 << 30)),
                    episodes=cfg.episodes_per_eval,
                    obs_mean=mean, obs_std=sd,
                ))
        results = ray_tpu.get(refs)
        returns = np.asarray([r[0] for r in results], np.float32).reshape(n, 2)
        env_steps = int(sum(r[1] for r in results))
        for _, _, cnt, s1, s2 in results:
            self._obs_count += cnt
            self._obs_sum += s1
            self._obs_sumsq += s2
        # top-k directions by best-of-pair; step scaled by the std of the
        # returns that actually enter the update (ARS's variance control)
        best = returns.max(axis=1)
        top = np.argsort(-best)[:k]
        used = returns[top]
        sigma_r = float(used.std()) or 1.0
        grad = ((used[:, 0] - used[:, 1])[:, None] * eps[top]).sum(axis=0) / (k * sigma_r)
        self.theta = self.theta + cfg.ars_lr * grad
        self._env_steps_lifetime += env_steps
        return {
            "episode_return_mean": float(returns.mean()),
            "episode_return_best": float(returns.max()),
            "num_evaluations": int(returns.size),
            "num_env_steps": env_steps,
            "return_std_topk": sigma_r,
        }

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp

        mean, sd = self._obs_stats()
        white = (np.asarray(obs, np.float32) - mean) / sd
        params = _unflatten(self.theta, self._template)
        logits = self.module.forward(params, jnp.asarray(white)[None])["logits"]
        return int(jnp.argmax(logits, axis=-1)[0])

    def save_to_path(self, path: str) -> str:
        import os
        import pickle

        super().save_to_path(path)
        with open(os.path.join(path, "obs_filter.pkl"), "wb") as f:
            pickle.dump(
                {"count": self._obs_count, "sum": self._obs_sum, "sumsq": self._obs_sumsq}, f
            )
        return path

    @classmethod
    def from_checkpoint(cls, path: str) -> "ARS":
        import os
        import pickle

        algo = super().from_checkpoint(path)
        fp = os.path.join(path, "obs_filter.pkl")
        if os.path.exists(fp):
            with open(fp, "rb") as f:
                st = pickle.load(f)
            algo._obs_count = st["count"]
            algo._obs_sum = st["sum"]
            algo._obs_sumsq = st["sumsq"]
        return algo


ARSConfig.algo_class = ARS
