"""MARWIL — monotonic advantage re-weighted imitation learning.

Equivalent of the reference's MARWIL
(reference: rllib/algorithms/marwil/marwil.py — offline RL that clones
expert actions weighted by exp(beta * advantage), so better-than-
average transitions dominate; beta=0 degenerates to plain BC). Rides
the BC offline machinery; the loss adds a value head trained on
discounted returns and the exponential advantage weighting.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.bc.bc import BC, BCConfig
from ray_tpu.rllib.core.learner.learner import Learner


class MARWILLearner(Learner):
    def compute_loss(self, params, batch):
        cfg = self.config
        out = self.module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(out["logits"])
        logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=1)[:, 0]

        vf = out["vf"]
        adv = batch["returns"] - vf
        vf_loss = jnp.mean(adv**2)
        # moving-average advantage norm (reference: marwil's ema of
        # squared advantages) approximated per-batch: stable enough for
        # the offline full-batch setting
        adv_norm = jnp.sqrt(jnp.mean(jax.lax.stop_gradient(adv) ** 2) + 1e-8)
        weights = jnp.exp(jnp.clip(cfg.beta * jax.lax.stop_gradient(adv) / adv_norm, -10.0, 10.0))
        pi_loss = -jnp.mean(weights * logp)
        loss = pi_loss + cfg.vf_coeff * vf_loss
        accuracy = jnp.mean((jnp.argmax(out["logits"], axis=-1) == batch["actions"]).astype(jnp.float32))
        return loss, {
            "total_loss": loss,
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "mean_weight": jnp.mean(weights),
            "accuracy": accuracy,
        }


def compute_returns(rewards: np.ndarray, dones: np.ndarray, gamma: float) -> np.ndarray:
    """Per-episode discounted reward-to-go over a flat trajectory stream."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        if dones[t]:
            acc = 0.0
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out


class MARWILConfig(BCConfig):
    learner_class = MARWILLearner

    def __init__(self):
        super().__init__()
        self.beta = 1.0  # 0 => plain BC
        self.vf_coeff = 1.0

    def offline(self, data=None):
        """data needs obs/actions plus either `returns` or
        rewards+dones (returns are derived with config.gamma)."""
        return super().offline(data)


class MARWIL(BC):
    config_class = MARWILConfig

    def __init__(self, config):
        data = config.offline_data
        if hasattr(data, "iter_batches"):  # a ray_tpu.data Dataset
            cols: Dict[str, list] = {}
            for b in data.iter_batches(batch_size=4096, batch_format="numpy"):
                for k, v in b.items():
                    cols.setdefault(k, []).append(np.asarray(v))
            data = {k: np.concatenate(v) for k, v in cols.items()}
        if not isinstance(data, dict) or "obs" not in data or "actions" not in data:
            raise ValueError(
                "MARWIL offline data needs obs/actions plus `returns` "
                "(or rewards+dones to derive them)"
            )
        if "returns" not in data:
            if "rewards" not in data or "dones" not in data:
                raise ValueError(
                    "MARWIL offline data needs obs/actions plus `returns`, "
                    "or rewards+dones to derive them"
                )
            data = dict(data)
            data["returns"] = compute_returns(
                np.asarray(data["rewards"], np.float32),
                np.asarray(data["dones"], bool),
                config.gamma,
            )
        config.offline_data = data
        super().__init__(config)
        self._batch["returns"] = np.asarray(data["returns"], np.float32)


MARWILConfig.algo_class = MARWIL
