from ray_tpu.rllib.algorithms.apex_dqn.apex_dqn import APEXDQN, APEXDQNConfig  # noqa: F401
