"""APEX-DQN — distributed prioritized experience replay.

Equivalent of the reference's Ape-X DQN
(reference: rllib/algorithms/apex_dqn/apex_dqn.py — Horgan et al.:
many actors generate n-step transitions WITH their own initial TD
priorities, sharded prioritized replay actors hold the data, and the
learner overlaps replay sampling/updates with actor collection).

Mapping onto this stack: env runners are `ApexEnvRunner` actors that
assemble n-step returns per env lane and score each transition with
the current network; replay shards are lightweight actors around
`PrioritizedReplayBuffer`; `training_step` kicks off the runners'
sample round, trains against the shards while that round is in flight
(one-ahead sample prefetch per shard), then lands the new transitions.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.rllib.env.off_policy_env_runner import OffPolicyEnvRunner


@ray_tpu.remote(num_cpus=0)
class ReplayShardActor:
    """One shard of the distributed prioritized replay
    (reference: apex uses `ReplayActor`s sharding a PER buffer)."""

    def __init__(self, capacity: int, alpha: float, beta: float, seed: int):
        from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer

        self.buf = PrioritizedReplayBuffer(capacity, alpha=alpha, beta=beta, seed=seed)

    def add(self, batch, priorities=None) -> int:
        self.buf.add_with_priorities(batch, priorities)
        return len(self.buf)

    def size(self) -> int:
        return len(self.buf)

    def sample(self, n: int):
        return self.buf.sample(n)

    def update_priorities(self, td) -> None:
        self.buf.update_priorities(np.asarray(td))


class ApexEnvRunner(OffPolicyEnvRunner):
    """Off-policy runner emitting n-step transitions with initial TD
    priorities. n-step windows are assembled per env LANE (the flat
    fragment batch interleaves envs, so composition happens here in the
    step loop where continuity is known)."""

    def __init__(self, config, worker_index: int = 0):
        super().__init__(config, worker_index)
        self._pending: List[List[list]] = [[] for _ in range(self.num_envs)]

    def _flush_lane(self, lane: List[list], rows: List[tuple], final_obs, terminated: bool):
        for obs0, act0, ret, depth in lane:
            rows.append((obs0, act0, ret, final_obs, terminated, depth))
        lane.clear()

    def sample(self) -> Dict[str, Any]:
        cfg = self.config
        T = cfg.rollout_fragment_length
        n_step, gamma = cfg.n_step, cfg.gamma
        self._on_fragment_start()

        rows: List[tuple] = []
        obs = self._obs
        prev_done = self._prev_done
        for _ in range(T):
            action, env_action = self._select_actions(obs)
            next_obs, reward, terminated, truncated, _ = self.env.step(env_action)
            done = terminated | truncated
            live = self._account_step(np.asarray(reward), done, prev_done)
            for i in range(self.num_envs):
                lane = self._pending[i]
                if not live[i]:
                    lane.clear()  # autoreset frame: stale action
                    continue
                r = float(reward[i])
                for e in lane:
                    e[2] += (gamma ** e[3]) * r
                    e[3] += 1
                lane.append([obs[i].astype(np.float32), action[i], r, 1])
                if terminated[i] or truncated[i]:
                    # episode end: every open window closes here; only a
                    # true termination stops the bootstrap
                    self._flush_lane(lane, rows, next_obs[i].astype(np.float32), bool(terminated[i]))
                elif lane[0][3] >= n_step:
                    obs0, act0, ret, depth = lane.pop(0)
                    rows.append((obs0, act0, ret, next_obs[i].astype(np.float32), False, depth))
            obs = next_obs
            prev_done = done
        self._obs = obs
        self._prev_done = prev_done

        if rows:
            batch = {
                "obs": np.stack([r[0] for r in rows]),
                "actions": np.asarray([r[1] for r in rows], np.int64),
                "rewards": np.asarray([r[2] for r in rows], np.float32),
                "next_obs": np.stack([r[3] for r in rows]),
                "terminateds": np.asarray([r[4] for r in rows], bool),
                # per-row bootstrap discount: gamma**depth — partial
                # windows flushed at truncation carry their true depth
                "discounts": np.asarray([gamma ** r[5] for r in rows], np.float32),
            }
            if getattr(cfg, "prioritized_replay", True):
                # initial priorities: |n-step TD error| under the CURRENT
                # net (reference: apex actors score before shipping).
                # Skipped for uniform replay (plain DQN n_step>1) — two
                # full-batch Q forwards the consumer would discard.
                q_now = np.asarray(self._q_fn(self.params, batch["obs"]))
                q_next = np.asarray(self._q_fn(self.params, batch["next_obs"]))
                q_sa = q_now[np.arange(len(rows)), batch["actions"]]
                target = batch["rewards"] + batch["discounts"] * (
                    1.0 - batch["terminateds"].astype(np.float32)
                ) * q_next.max(axis=-1)
                priorities = np.abs(target - q_sa)
            else:
                priorities = None
        else:
            batch, priorities = None, None

        n = len(rows)
        self._global_step += n
        metrics = self._drain_episode_metrics(n, self._weights_seq)
        metrics.update(self._extra_metrics())
        return {"batch": batch, "metrics": metrics, "priorities": priorities}


class APEXDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.env_runner_cls = ApexEnvRunner
        self.num_env_runners = 2
        self.num_replay_shards = 2
        self.n_step = 3
        self.prioritized_replay = True  # the replay shards are always PER
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        # the n-step return already spans n transitions: the learner's
        # gamma must be gamma**n_step on the bootstrap term — handled by
        # passing an effective gamma to the learner below
        self.train_batch_size = 64
        self.training_intensity = 1.0
        self.target_network_update_freq = 500


class APEXDQN(DQN):
    """training_step overlaps replay-shard training with the runners'
    in-flight sample round (reference: apex_dqn.py training_step)."""

    config_class = APEXDQNConfig

    def __init__(self, config):
        if config.num_env_runners < 1:
            raise ValueError("APEX requires remote env runners (num_env_runners >= 1)")
        # remote learners are fine: LearnerGroup.get_td_errors gathers
        # per-shard TD errors from lockstep workers, so shard priorities
        # refresh under num_learners > 0 exactly like the local path
        # DQN.__init__ builds a LOCAL replay we don't use; skip straight
        # to Algorithm init then attach shards
        from ray_tpu.rllib.algorithms.algorithm import Algorithm

        Algorithm.__init__(self, config)
        # n-step discounting: each batch row carries its own bootstrap
        # discount (gamma**depth, see ApexEnvRunner) which the DQN
        # learner prefers over its scalar cfg.gamma — truncation-flushed
        # partial windows bootstrap with their true depth
        self.shards = [
            ReplayShardActor.remote(
                config.replay_buffer_capacity // config.num_replay_shards,
                config.prioritized_replay_alpha,
                config.prioritized_replay_beta,
                config.seed + i,
            )
            for i in range(config.num_replay_shards)
        ]
        self._rr = 0
        self._last_sampled = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        group = self.env_runner_group

        # 1. weights out, then kick off the sample round WITHOUT waiting
        self._weights_seq += 1
        group.sync_weights(
            self.learner_group.get_weights(), self._weights_seq,
            global_step=self._env_steps_lifetime,
        )
        sample_refs = [r.sample.remote() for r in group.remote_runners]

        # 2. train against the shards while the round is in flight,
        # one-ahead prefetch so sampling and updating overlap
        acc: Dict[str, list] = {}
        sizes = ray_tpu.get([s.size.remote() for s in self.shards])
        warm = sum(sizes) >= cfg.num_steps_sampled_before_learning_starts
        if warm:
            num_updates = max(1, int(self._last_sampled * cfg.training_intensity / cfg.train_batch_size))
            order = [self.shards[(self._rr + u) % len(self.shards)] for u in range(num_updates)]
            self._rr = (self._rr + num_updates) % len(self.shards)
            pending = order[0].sample.remote(cfg.train_batch_size)
            for u, shard in enumerate(order):
                # generous timeout: on the 1-core CI box a full-suite run
                # can starve this actor round-trip for minutes
                batch = ray_tpu.get(pending, timeout=300)
                nxt = order[u + 1] if u + 1 < len(order) else None
                if nxt is not None and nxt is not shard:
                    # prefetch only from a DIFFERENT shard: the buffer's
                    # update_priorities applies to its last sample, so a
                    # same-shard prefetch must wait until the priority
                    # push below is enqueued (actor calls are FIFO)
                    pending = nxt.sample.remote(cfg.train_batch_size)
                for k, v in self.learner_group.update_once(batch).items():
                    acc.setdefault(k, []).append(v)
                td = self.learner_group.get_td_errors()
                if td is not None:
                    shard.update_priorities.remote(td)
                if nxt is not None and nxt is shard:
                    pending = nxt.sample.remote(cfg.train_batch_size)

        # 3. land the finished sample round on the shards
        samples = ray_tpu.get(sample_refs, timeout=300)
        sampled = 0
        for s in samples:
            if s["batch"] is not None:
                shard = self.shards[self._rr % len(self.shards)]
                self._rr += 1
                shard.add.remote(s["batch"], s["priorities"])
                sampled += len(s["batch"]["actions"])
        self._last_sampled = sampled

        results = self._fold_sample_metrics(samples)
        results["epsilon"] = samples[0]["metrics"].get("epsilon")
        results["learner"] = {k: float(np.mean(v)) for k, v in acc.items()}
        results["replay_shard_sizes"] = sizes
        return results

    def stop(self) -> None:
        super().stop()
        for s in self.shards:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass


APEXDQNConfig.algo_class = APEXDQN
