"""CRR — critic-regularized regression (discrete offline RL).

Equivalent of the reference's CRR
(reference: rllib/algorithms/crr/ — Wang et al. 2020: an actor trained
by ADVANTAGE-FILTERED behavior cloning against a TD-trained critic, so
the policy imitates only the dataset actions the critic scores above
the policy's own expectation; nothing is ever queried outside the data
support, which is what makes it safe offline).

Jax-native: critic (Q over all actions), target critic and actor are
explicit pytrees; one jitted update does the expected-SARSA TD step
(bootstrap under the CURRENT actor's distribution), the advantage
filter (binary or exp(A/beta)), and the weighted log-likelihood actor
step. The offline minibatch loop mirrors CQL's (cql.py) — fixed
transition dataset, no env runners.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig

_COLS = ("obs", "actions", "next_obs", "rewards", "terminateds")


class CRRConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.gamma = 0.99
        self.hidden = (256, 256)
        # "binary": imitate only positive-advantage actions (1[A>0]);
        # "exp": softer exp(A/beta) weights clipped at weight_clip
        self.advantage_mode = "binary"
        self.beta = 1.0
        self.weight_clip = 20.0
        self.target_network_update_freq = 100
        self.train_batch_size = 256
        self.updates_per_iteration = 200
        self.offline_data: Dict[str, Any] = {}

    def offline(self, data=None):
        """data: transition arrays {obs, actions, next_obs, rewards,
        terminateds} (actions int) or a ray_tpu.data Dataset."""
        if data is not None:
            self.offline_data = data
        return self

    def copy(self) -> "CRRConfig":
        data, self.offline_data = self.offline_data, {}
        try:
            out = super().copy()
        finally:
            self.offline_data = data
        out.offline_data = data
        return out


class CRR(Algorithm):
    config_class = CRRConfig

    def __init__(self, config: CRRConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.utils.env import env_spaces

        data = config.offline_data
        if hasattr(data, "iter_batches"):
            parts: Dict[str, list] = {c: [] for c in _COLS}
            for b in data.iter_batches(batch_size=4096, batch_format="numpy"):
                for c in _COLS:
                    parts[c].append(np.asarray(b[c]))
            data = {c: np.concatenate(parts[c]) for c in _COLS}
        missing = [c for c in _COLS if c not in data]
        if missing:
            raise ValueError(f"CRR offline data missing columns {missing}")
        self.config = config
        self.env_runner_group = None
        self.learner_group = None
        self._iteration = 0
        self._weights_seq = 0
        self._env_steps_lifetime = 0
        self._recent_returns: list = []
        self._spaces = env_spaces(config)
        obs_dim = int(np.prod(self._spaces[0].shape))
        self.n_actions = int(self._spaces[1].n)
        self._data = {
            "obs": np.asarray(data["obs"], np.float32),
            "actions": np.asarray(data["actions"], np.int64),
            "next_obs": np.asarray(data["next_obs"], np.float32),
            "rewards": np.asarray(data["rewards"], np.float32),
            "terminateds": np.asarray(data["terminateds"], np.float32),
        }
        self._np_rng = np.random.default_rng(config.seed)

        def mlp_init(key, sizes, out):
            dims = list(sizes) + [out]
            keys = jax.random.split(key, len(dims))
            layers = []
            d_in = obs_dim
            for i, d_out in enumerate(dims):
                scale = 0.01 if i == len(dims) - 1 else (2.0 / d_in) ** 0.5
                layers.append({
                    "w": jax.random.normal(keys[i], (d_in, d_out)) * scale,
                    "b": jnp.zeros((d_out,)),
                })
                d_in = d_out
            return layers

        def mlp(layers, x):
            for layer in layers[:-1]:
                x = jax.nn.relu(x @ layer["w"] + layer["b"])
            return x @ layers[-1]["w"] + layers[-1]["b"]

        cfg = config
        rng = jax.random.PRNGKey(cfg.seed)
        k_q, k_pi = jax.random.split(rng)
        self.params = {
            "q": mlp_init(k_q, cfg.hidden, self.n_actions),
            "pi": mlp_init(k_pi, cfg.hidden, self.n_actions),
        }
        self.target_q = jax.tree.map(jnp.asarray, self.params["q"])
        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self.params)
        self._updates = 0
        self._mlp = mlp

        def loss_fn(params, target_q, batch):
            obs, a = batch["obs"], batch["actions"]
            q_all = mlp(params["q"], obs)                        # [B, A]
            q_sa = jnp.take_along_axis(q_all, a[:, None], 1)[:, 0]
            logits = mlp(params["pi"], obs)
            logp_all = jax.nn.log_softmax(logits)
            pi = jnp.exp(logp_all)

            # critic: expected SARSA under the CURRENT actor at s'
            next_logits = mlp(params["pi"], batch["next_obs"])
            next_pi = jax.nn.softmax(next_logits)
            q_next_t = mlp(target_q, batch["next_obs"])
            v_next = jnp.sum(jax.lax.stop_gradient(next_pi) * q_next_t, -1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["terminateds"]) * v_next
            critic_loss = jnp.mean((q_sa - jax.lax.stop_gradient(target)) ** 2)

            # actor: advantage-filtered behavior cloning. The advantage
            # uses the critic detached — the filter must not push Q.
            q_det = jax.lax.stop_gradient(q_all)
            adv = jnp.take_along_axis(q_det, a[:, None], 1)[:, 0] - jnp.sum(
                jax.lax.stop_gradient(pi) * q_det, -1
            )
            if cfg.advantage_mode == "binary":
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.clip(jnp.exp(adv / cfg.beta), 0.0, cfg.weight_clip)
            logp_a = jnp.take_along_axis(logp_all, a[:, None], 1)[:, 0]
            actor_loss = -jnp.mean(w * logp_a)
            loss = critic_loss + actor_loss
            stats = {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "mean_advantage_weight": jnp.mean(w),
                "mean_q": jnp.mean(q_sa),
            }
            return loss, stats

        def update(params, target_q, opt_state, batch):
            (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_q, batch
            )
            upd, opt_state = self._opt.update(grads, opt_state)
            return optax.apply_updates(params, upd), opt_state, stats

        self._update = jax.jit(update)
        self._pi_fn = jax.jit(lambda p, obs: mlp(p["pi"], obs))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = len(self._data["actions"])
        acc: Dict[str, list] = {}
        for _ in range(cfg.updates_per_iteration):
            idx = self._np_rng.integers(0, n, size=min(cfg.train_batch_size, n))
            batch = {k: v[idx] for k, v in self._data.items()}
            self.params, self._opt_state, stats = self._update(
                self.params, self.target_q, self._opt_state, batch
            )
            self._updates += 1
            if self._updates % cfg.target_network_update_freq == 0:
                self.target_q = self.params["q"]
            # append DEVICE arrays; one conversion at the end — a float()
            # per update would force a host sync inside the hot loop
            for k, v in stats.items():
                acc.setdefault(k, []).append(v)
        return {
            "learner": {k: float(np.mean([np.asarray(x) for x in v])) for k, v in acc.items()},
            "episode_return_mean": float("nan"),
            "num_offline_samples": n,
        }

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp

        logits = self._pi_fn(self.params, jnp.asarray(obs, jnp.float32).reshape(1, -1))
        return int(np.asarray(jnp.argmax(logits, -1))[0])

    def stop(self) -> None:
        pass


CRRConfig.algo_class = CRR
